"""Continuous-batching inference engine over the KV-cached GPT-2
decoder (the Orca/vLLM iteration-level scheduler, shape-stable for
TPU; round 6).

The offline path (models/gpt2_decode.generate) assembles one static
batch and runs prefill + a compiled scan to the LAST row's length:
every caller blocks until the slowest row finishes, and a new prompt
cannot enter until the whole batch drains.  This engine inverts that
control flow:

* **slot pool** — a fixed pool of ``max_slots`` rows backed by ONE
  preallocated KV-cache arena of shape ``(L, max_slots, H_kv,
  max_len, D)`` per K/V.  Every jitted function below is keyed only on
  ``(max_slots, max_len)`` and the model statics, so the engine NEVER
  recompiles at runtime — admission, decode, and retirement all happen
  inside the same three executables;
* **iteration-level step loop** — each ``step()`` advances every live
  slot by one token (one batched call over the whole pool), retires
  rows that hit their token budget IMMEDIATELY, and backfills the
  freed slots from the scheduler queue in the SAME step (prefill one
  row, write it into the arena at the free slot index);
* **exactness** — a slot runs the same per-row math as single-prompt
  ``generate``: prefill over a (1, max_len) padded row, then
  gpt2_decode.decode_step per token, with the request's private
  sampling-key chain split exactly as the offline path splits it.
  tests/test_serve.py asserts token-for-token identity against
  ``generate`` for greedy AND seeded-sampling requests.

Why it wins: the static batch pays ``Σ_batches max(new_tokens)``
pool-wide steps while the engine pays ~``Σ new_tokens / max_slots`` —
the gap is the per-batch straggler tail plus the slots that sat idle
behind it (bench_serve.py measures it on a ragged workload).

Fast decode (perf round): the offline path's measured decode wins now
run inside the engine too —

* **int8 KV arenas** (``cache_dtype="int8"``): the pool arena stores
  (int8 values, f32 per-(token, head) scales) tuples, halving cache
  bytes on a cache-read-bound loop; every executable is shape-agnostic
  between dense and quantized arenas (pytree-mapped), and engine
  streams are byte-identical to offline ``generate(...,
  cache_dtype="int8")``;
* **speculative decoding** (``draft_model=``, ``spec_k=``): each
  ``step()`` runs spec_k sequential DRAFT decode steps and ONE target
  chunk verify (``_advance_chunk`` — a single cache read serves spec_k
  positions), emitting up to spec_k tokens per step.  Greedy requests
  accept by argmax match (byte-identical streams to non-speculative
  serve, same near-tie caveat as ``generate_speculative``); sampled
  requests go through rejection sampling (``gpt2_decode.spec_verify``:
  accept with min(1, p/q), resample the residual) so every emitted
  token is distributed exactly as direct target sampling.  Multi-token
  steps change the downstream accounting: retire fires per TOKEN
  (budget/stop mid-chunk), ``on_token`` streams per accepted token,
  and TPOT becomes tokens-per-step aware (stats.py).

Paged KV (memory-model round): ``paged=PagedConfig(...)`` swaps the
worst-case slot arena for ONE block-paged pool (serve/paged.py)
shared with the prefix cache — a request's KV is a block list grown
as decode advances, admission is bounded by blocks free rather than
slots free, and pool pressure PREEMPTS (swap a request's blocks to
host byte-exactly, resume later) instead of stalling.  The paged pool
steps vmap the same ``_decode_row``/``_spec_row`` math the slot-arena
steps do, so the two memory models produce bit-identical streams.

Long-context serving (the long-context round; docs/SERVING.md
"Long-context serving"):

* **chunked-prefill token budget**
  (``PagedConfig(prefill_token_budget=)``): a Sarathi-style per-step
  prefill TOKEN budget — an admission whose prompt exceeds it splits
  across consecutive steps in block-width ``_chunk_row`` windows
  (bitwise the unbudgeted prefill), so one 32k document admission
  never stalls the live decode lanes for more than one chunk per
  step (the request ledger's stall phase is the proof metric);
* **windowed paged decode**: sliding-window models
  (``GPT2Config(attn_window=W)``) serve on the PAGED engine — block
  tables drop fully-out-of-window blocks back to the free list as
  ``pos`` advances, so a long chat holds O(window) blocks whatever
  its length, and the block-native kernel masks + loop-bounds the
  attention to the window;
* **ring-attention prefill** (``TPConfig(ring_prefill=True)``): cold
  long-prompt admissions on a TP engine prefill SEQUENCE-sharded
  over the mesh (parallel/ring_attention.py), for prompts beyond one
  shard's flash tile.

Scope: dense/GQA/MoE models (everything _advance_one supports with a
position-indexed dense cache).  Sliding-window models serve in paged
mode only (windowed without ``paged=``, windowed + prefix cache, and
windowed + ``kernel="gather"`` stay rejected typed);
repetition_penalty/min_p are offline-only knobs.  int8 arenas compose
with the prefix cache since the paged round (pytree-generic block
pools; cache-enabled int8 engines route every admission through the
chunked canonical form — see _admit).
"""

from __future__ import annotations

import inspect
import itertools
import math
import time
from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2_decode import (_advance_chunk, _advance_one,
                                  _filter_logits, _logits, _norm_window,
                                  _quant_flag, _sample, decode_step,
                                  extract_params, prefill, prefill_chunk,
                                  spec_verify)
from ..observe import monitor as _monitor
from ..observe import requests as _reqs
from ..observe import stepprof as _stepprof
from ..observe import trace as _trace
from ..resilience import faults as _faults
from ..utils.logging import get_channel
from .fork import BranchHandle, ForkHandle
from .paged import (PagedConfig, PagedKVArena, _aot_call,
                    _paged_decode_kernel, _paged_decode_step,
                    _paged_spec_kernel, _paged_spec_step)
from .prefix import (PrefixCache, PrefixCacheConfig, SessionHandle,
                     _read_slot)
from .request import (DeadlineExceededError, EngineFailedError,
                      GenerationRequest, GenerationResult, LoadShedError,
                      RequestHandle)
from .scheduler import FIFOScheduler, PriorityScheduler
from .stats import EngineStats


def _select_sample(logit, key, temp, top_k, top_p, use_top_p,
                   mask=None):
    """Per-row sampling with a TRACED greedy flag.  The offline paths
    bake ``greedy`` in as a static (one compile per mode); a slot pool
    mixes greedy and sampled requests in one executable, so compute
    both branches of the SAME ``_sample`` the offline path uses and
    select — the greedy branch is argmax over the identical f32 logit,
    the sampled branch divides by max(temp, 1e-6) exactly as
    ``generate`` does, so either way the chosen token matches the
    offline token bit for bit.  ``mask`` (V,) bool or None is the
    constrained-decoding vocab mask, forwarded to the shared
    ``_sample`` (None / all-True are bitwise no-ops)."""
    g = _sample(logit, key, temp, top_p, True, top_k, use_top_p,
                mask=mask)
    s = _sample(logit, key, jnp.maximum(temp, 1e-6), top_p, False,
                top_k, use_top_p, mask=mask)
    return jnp.where(temp <= 0.0, g, s).astype(jnp.int32)


def _decode_row(params, kc_r, vc_r, tok, pos_r, live_r, key, temp,
                top_p, n_head, eps, moe_top_k, top_k, use_top_p,
                tp_axis=None, tp_world=1, ep=None, mask=None,
                with_lp=False):
    """ONE slot's decode-step math — kc_r/vc_r: (L, H_kv, max_len, D)
    cache rows (int8 arenas are (values, scales) pytrees, so the
    batch-axis insert/strip is tree-mapped rather than indexed).
    Shared by the slot-arena pool step below AND the paged pool step
    (serve/paged.py), so the two memory models run literally the same
    per-row ops and cannot drift.  ``tp_axis``/``tp_world`` thread the
    tensor-parallel mesh axis through (serve/tp.py's sharded twins;
    defaults leave the serial math bit-identical)."""
    p_c = jnp.where(live_r, pos_r, 0)
    t_c = jnp.where(live_r, tok, 0)
    x = (params["wte"][t_c] + params["wpe"][p_c])[None, None, :]
    logits, kc2, vc2 = decode_step(
        params, x, jax.tree.map(lambda a: a[:, None], kc_r),
        jax.tree.map(lambda a: a[:, None], vc_r), p_c, n_head, eps,
        moe_top_k=moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
        ep=ep)
    ks = jax.random.split(key)
    nxt = _select_sample(logits[0], ks[0], temp, top_k, top_p,
                         use_top_p, mask=mask)
    out = (nxt, jax.tree.map(lambda a: a[:, 0], kc2),
           jax.tree.map(lambda a: a[:, 0], vc2), ks[1])
    if with_lp:
        # chosen-token logprob under the RAW model distribution (not
        # the filtered one) — the fork round's best-of-n ranking
        # signal; an extra output, never an input, so the sampled
        # token chain is untouched
        lp = jax.nn.log_softmax(
            logits[0].astype(jnp.float32))[nxt]
        out = out + (lp,)
    return out


@partial(jax.jit,
         static_argnames=("n_head", "eps", "moe_top_k", "top_k",
                          "use_top_p", "tp_axis", "tp_world"),
         donate_argnums=(1, 2))
def _pool_decode_step(params, kc, vc, toks, pos, live, keys, temps,
                      top_p, n_head, eps, moe_top_k, top_k, use_top_p,
                      tp_axis=None, tp_world=1):
    """Advance EVERY slot one token: toks/pos/live/temps (S,), keys
    (S, 2), arenas (L, S, H_kv, max_len, D) — donated, so the arena
    updates in place across steps.  Dead slots run the same math on
    clamped inputs (fixed shapes; their cache rows are garbage that
    the next admission's full-row prefill write overwrites) and their
    outputs are ignored host-side.  Returns (next_toks, kc, vc,
    new_keys)."""

    def row(kc_r, vc_r, tok, pos_r, live_r, key, temp):
        return _decode_row(params, kc_r, vc_r, tok, pos_r, live_r,
                           key, temp, top_p, n_head, eps, moe_top_k,
                           top_k, use_top_p, tp_axis=tp_axis,
                           tp_world=tp_world)

    return jax.vmap(row, in_axes=(1, 1, 0, 0, 0, 0, 0),
                    out_axes=(0, 1, 1, 0))(kc, vc, toks, pos, live,
                                           keys, temps)


@partial(jax.jit,
         static_argnames=("n_head", "eps", "moe_top_k", "top_k",
                          "use_top_p", "quant", "window", "tp_axis",
                          "tp_world"))
def _prefill_one(params, ids, prompt_len, key, temp, top_p, n_head,
                 eps, moe_top_k, top_k, use_top_p, quant=False,
                 window=None, tp_axis=None, tp_world=1, ep=None,
                 mask=None):
    """Admission prefill for ONE request: ids (1, max_len)
    right-padded.  Returns (first token, carried key, kc_row, vc_row)
    with cache rows (L, 1, H_kv, max_len, D) ready to write into the
    arena ((values, scales) tuples when ``quant`` — the int8 arena
    mode).  ``prompt_len`` is traced, so every admission reuses one
    executable regardless of prompt length.  ``window``: banded
    (sliding-window) prefill with a LINEAR cache layout
    (``rolling=False`` — the paged engine's block tables address
    positions directly; the offline rolling layout would scramble
    them)."""
    hidden, kc, vc = prefill(params, ids, n_head, eps,
                             moe_top_k=moe_top_k, quant_cache=quant,
                             window=window, rolling=False,
                             tp_axis=tp_axis, tp_world=tp_world,
                             ep=ep)
    last_h = jax.lax.dynamic_index_in_dim(
        hidden, prompt_len - 1, axis=1, keepdims=False)      # (1, E)
    logit0 = _logits(last_h[:, None, :], params)[0, 0]       # (V,)
    ks = jax.random.split(key)
    tok0 = _select_sample(logit0, ks[0], temp, top_k, top_p, use_top_p,
                          mask=mask)
    return tok0, ks[1], kc, vc


@partial(jax.jit,
         static_argnames=("n_head", "eps", "moe_top_k", "top_k",
                          "use_top_p", "quant", "window", "tp_axis",
                          "tp_world"))
def _prefill_batch(params, ids, plens, seeds, temps, top_p, n_head,
                   eps, moe_top_k, top_k, use_top_p, quant=False,
                   window=None, tp_axis=None, tp_world=1, ep=None):
    """BATCHED cold admission (the gather-tax round): R requests'
    prefills in ONE dispatch — ids (R, W) right-padded at the pass's
    shared narrow width, plens/seeds/temps (R,).  vmaps the exact
    :func:`_prefill_one` row body (key chain included: PRNGKey(seed)
    -> split -> sample/carry, moved inside the executable), so every
    row's (tok0, carried key, cache rows) is BITWISE the per-request
    call's — pinned by tests/test_paged.py::test_prefill_batch
    _bitwise_equals_single.  One scheduling pass that admits K
    requests pays one dispatch + one host sync instead of K, which
    is what keeps an arrival burst from stalling live decode lanes
    (the paged bench's TPOT tax).  Returns (tok0 (R,), keys (R, 2),
    kc rows (L, R, H, W, D), vc rows) — the caller scatters each
    row's lanes into its freshly-allocated blocks."""
    def row(ids_r, plen, seed, temp):
        key0 = jax.random.split(jax.random.PRNGKey(seed), 1)[0]
        return _prefill_one.__wrapped__(
            params, ids_r[None], plen, key0, temp, top_p, n_head,
            eps, moe_top_k, top_k, use_top_p, quant=quant,
            window=window, tp_axis=tp_axis, tp_world=tp_world, ep=ep)

    tok0, keys, kc, vc = jax.vmap(row, in_axes=(0, 0, 0, 0),
                                  out_axes=(0, 0, 1, 1))(
        ids, plens, seeds, temps)
    sq = lambda a: a[:, :, 0]   # drop the vmapped rows' B=1 axis
    return tok0, keys, jax.tree.map(sq, kc), jax.tree.map(sq, vc)


@partial(jax.jit,
         static_argnames=("n_head", "eps", "moe_top_k", "quant"))
def _prefill_rows(params, ids, n_head, eps, moe_top_k, quant=False):
    """DRAFT-side admission prefill: cache rows only, no sampling (the
    draft first proposes from the next spec step's state; the
    admission token is always the TARGET's, sampled by ``_prefill_one``
    / the warm path — which is what keeps spec admission tokens
    byte-identical to non-speculative admission)."""
    _, kc, vc = prefill(params, ids, n_head, eps, moe_top_k=moe_top_k,
                        quant_cache=quant)
    return kc, vc


@partial(jax.jit,
         static_argnames=("n_head", "eps", "moe_top_k", "chunk",
                          "window", "tp_axis", "tp_world"),
         donate_argnums=(2, 3))
def _chunk_row(params, ids, kc_row, vc_row, off, n_head, eps,
               moe_top_k, chunk, window=None, tp_axis=None,
               tp_world=1, ep=None):
    """Offset prefill of ONE block-width window: embed tokens at
    positions [off, off+chunk) of the padded ``ids`` row and advance
    them through ``gpt2_decode.prefill_chunk`` against a cache row
    that already holds canonical K/V below ``off``.  ``off`` is
    traced, so every warm admission's every window rides one
    executable.  Returns ((1, chunk, E) final-LN hidden, kc_row,
    vc_row) — rows donated, the warm-admission loop rebinds."""
    toks = jax.lax.dynamic_slice(ids, (0, off), (1, chunk))
    pos = off + jnp.arange(chunk)
    x = jnp.take(params["wte"], toks[0], axis=0)[None] + \
        jnp.take(params["wpe"], pos, axis=0)[None]
    return prefill_chunk(params, x, kc_row, vc_row, off, n_head, eps,
                         moe_top_k=moe_top_k, window=window,
                         tp_axis=tp_axis, tp_world=tp_world, ep=ep)


@partial(jax.jit, static_argnames=("top_k", "use_top_p"))
def _first_from_hidden(params, hidden, row, key, temp, top_p, top_k,
                       use_top_p, mask=None):
    """Sample the admission token from a chunk's hidden block: row
    ``row`` of ``hidden`` (1, chunk, E) is position prompt_len-1.
    Mirrors the tail of ``_prefill_one`` exactly — same (1, 1, E)
    logits projection, same key split, same ``_select_sample`` — so a
    warm admission's first token matches the cold path's bit for bit
    given a bitwise-equal hidden row."""
    last_h = jax.lax.dynamic_index_in_dim(hidden, row, axis=1,
                                          keepdims=False)     # (1, E)
    logit0 = _logits(last_h[:, None, :], params)[0, 0]        # (V,)
    ks = jax.random.split(key)
    tok0 = _select_sample(logit0, ks[0], temp, top_k, top_p, use_top_p,
                          mask=mask)
    return tok0, ks[1]


def _batch1(c):
    """Insert the width-1 batch axis on a cache pytree (dense arrays
    or (values, scales) tuples)."""
    return jax.tree.map(lambda a: a[:, None], c)


def _unbatch1(c):
    return jax.tree.map(lambda a: a[:, 0], c)


def _draft_propose(d_params, dkc_r, dvc_r, t_c, p_c, k_draft, temp,
                   top_p, spec_k, dn, de, dm, top_k, use_top_p):
    """The DRAFT half of one slot's speculative chunk: ``spec_k``
    sequential draft decode steps propose ``spec_k - 1`` tokens (the
    extra step processes the last proposal as an input so a
    full-accept chunk leaves the draft cache a valid row ahead — the
    same trick as the offline ``_spec_row``).  Shared by the
    slot-arena spec row and the paged-kernel spec row, so the
    proposal chain (and therefore the verify outcome) cannot drift
    between memory models.  Returns (props (spec_k-1,), d_probs
    (spec_k-1, V), dkc_b, dvc_b) with the draft rows batched."""
    ts = jnp.maximum(temp, 1e-6)

    def dstep(c, k):
        dkc_b, dvc_b, tok_, dpos = c
        x = (d_params["wte"][tok_] + d_params["wpe"][dpos])[None, None]
        lg, dkc_b, dvc_b = _advance_one(d_params, x, dkc_b, dvc_b,
                                        dpos, dn, de, moe_top_k=dm)
        # post-filter draft distribution (the q of the accept
        # ratio) AND the proposal drawn from it — the identical
        # filter chain _sample uses, via the shared helper
        fl = _filter_logits(lg[0], ts, top_p, top_k, use_top_p)
        nxt_s = jax.random.categorical(k, fl).astype(jnp.int32)
        nxt_g = jnp.argmax(lg[0]).astype(jnp.int32)
        nxt = jnp.where(temp <= 0.0, nxt_g, nxt_s)
        return ((dkc_b, dvc_b, nxt, dpos + 1),
                (nxt, jax.nn.softmax(fl)))

    dkeys = jax.random.split(k_draft, spec_k)
    (dkc_b, dvc_b, _, _), (props_all, q_all) = jax.lax.scan(
        dstep, (_batch1(dkc_r), _batch1(dvc_r), t_c, p_c), dkeys)
    return props_all[:-1], q_all[:-1], dkc_b, dvc_b


def _spec_row(t_params, d_params, kc_r, vc_r, dkc_r, dvc_r, tok, pos_r,
              live_r, key, temp, top_p, spec_k, tn, te, tm, dn, de, dm,
              top_k, use_top_p, tp_axis=None, tp_world=1, ep=None):
    """ONE slot's speculative-chunk math: the shared draft proposal
    scan (:func:`_draft_propose`), then ONE target chunk advance
    (``_advance_chunk`` — a single cache read serves all ``spec_k``
    positions), then :func:`~singa_tpu.models.gpt2_decode.spec_verify`
    decides the accept count: greedy match for ``temp <= 0`` rows,
    rejection sampling with residual resample for sampled rows — both
    in the SAME executable (temp is traced, like ``_select_sample``).
    Shared by the slot-arena spec step and the paged GATHER spec step
    (serve/paged.py) — one definition, no drift; the paged BLOCK
    kernel's row is :func:`_spec_row_paged` below (same draft scan
    and verify, chunk-query block-native target attention)."""
    p_c = jnp.where(live_r, pos_r, 0)
    t_c = jnp.where(live_r, tok, 0)
    k_draft, k_verify, k_next = jax.random.split(key, 3)
    props, d_probs, dkc_b, dvc_b = _draft_propose(
        d_params, dkc_r, dvc_r, t_c, p_c, k_draft, temp, top_p,
        spec_k, dn, de, dm, top_k, use_top_p)

    chunk_toks = jnp.concatenate([t_c[None], props])
    xs = (jnp.take(t_params["wte"], chunk_toks, axis=0)
          + jnp.take(t_params["wpe"],
                     p_c + jnp.arange(spec_k), axis=0))[None]
    # only the TARGET side shards under TP (serve/tp.py): the draft
    # scan above runs replicated on every shard (same inputs → same
    # proposals bitwise), which is what keeps any draft geometry legal
    # whatever the tp width
    lg, kc2, vc2 = _advance_chunk(t_params, xs, _batch1(kc_r),
                                  _batch1(vc_r), p_c, tn, te,
                                  moe_top_k=tm, tp_axis=tp_axis,
                                  tp_world=tp_world, ep=ep)
    out, a_draft = spec_verify(lg[0], d_probs, props, k_verify,
                               temp, top_p, top_k, use_top_p)
    return (out, a_draft, _unbatch1(kc2), _unbatch1(vc2),
            _unbatch1(dkc_b), _unbatch1(dvc_b), k_next)


def _decode_row_paged(params, pool_k, pool_v, tbl, tok, pos_r, live_r,
                      key, temp, top_p, n_blk, block, trash, n_head,
                      eps, moe_top_k, top_k, use_top_p, window=None,
                      blk_lo=None, tp_axis=None, tp_world=1, ep=None,
                      mask=None, with_lp=False):
    """ONE slot's BLOCK-NATIVE decode-step math (the gather-tax
    round): same embed / sample chain as :func:`_decode_row`, but the
    attention runs directly over the block pool through
    ``gpt2_decode.decode_step_paged`` — no materialized row, and the
    only cache state returned is the one (L, H_kv, B, D) block the
    step wrote (read-modify-write, so untouched lanes stay byte
    copies).  Logits agree with the gather path to float
    reduction-order (online softmax), which is token-identity away
    from exact argmax/CDF ties — the parity pin tests/test_paged.py
    holds the kernel to."""
    from ..models.gpt2_decode import decode_step_paged

    p_c = jnp.where(live_r, pos_r, 0)
    t_c = jnp.where(live_r, tok, 0)
    x = (params["wte"][t_c] + params["wpe"][p_c])[None, None, :]
    logits, kb, vb = decode_step_paged(
        params, x, pool_k, pool_v, tbl, p_c, n_blk, n_head, eps,
        block=block, trash=trash, moe_top_k=moe_top_k,
        window=window, blk_lo=blk_lo,
        tp_axis=tp_axis, tp_world=tp_world, ep=ep)
    ks = jax.random.split(key)
    nxt = _select_sample(logits[0], ks[0], temp, top_k, top_p,
                         use_top_p, mask=mask)
    if with_lp:
        lp = jax.nn.log_softmax(
            logits[0].astype(jnp.float32))[nxt]
        return nxt, kb, vb, ks[1], lp
    return nxt, kb, vb, ks[1]


def _spec_row_paged(t_params, d_params, pool_k, pool_v, dkc_r, dvc_r,
                    tbl, tok, pos_r, live_r, key, temp, top_p, n_blk,
                    spec_k, block, trash, tn, te, tm, dn, de, dm,
                    top_k, use_top_p, window=None, blk_lo=None,
                    tp_axis=None, tp_world=1, ep=None):
    """ONE slot's BLOCK-NATIVE speculative chunk: the SAME draft
    proposal scan and the SAME ``spec_verify`` as :func:`_spec_row`
    (shared helpers — the accept logic cannot drift), with the target
    chunk advance running block-natively over the pool
    (``gpt2_decode.chunk_step_paged`` — the chunk-query variant of
    the online-softmax accumulator).  Returns the DOUBLE blocks the
    chunk wrote (kdbl/vdbl, (L, H_kv, 2B, D)-stacked); the pool step
    splits the halves and scatters them."""
    from ..models.gpt2_decode import chunk_step_paged

    p_c = jnp.where(live_r, pos_r, 0)
    t_c = jnp.where(live_r, tok, 0)
    k_draft, k_verify, k_next = jax.random.split(key, 3)
    props, d_probs, dkc_b, dvc_b = _draft_propose(
        d_params, dkc_r, dvc_r, t_c, p_c, k_draft, temp, top_p,
        spec_k, dn, de, dm, top_k, use_top_p)

    chunk_toks = jnp.concatenate([t_c[None], props])
    xs = (jnp.take(t_params["wte"], chunk_toks, axis=0)
          + jnp.take(t_params["wpe"],
                     p_c + jnp.arange(spec_k), axis=0))[None]
    lg, kdbl, vdbl = chunk_step_paged(
        t_params, xs, pool_k, pool_v, tbl, p_c, n_blk, tn, te,
        block=block, trash=trash, moe_top_k=tm, window=window,
        blk_lo=blk_lo, tp_axis=tp_axis, tp_world=tp_world, ep=ep)
    out, a_draft = spec_verify(lg[0], d_probs, props, k_verify,
                               temp, top_p, top_k, use_top_p)
    return (out, a_draft, kdbl, vdbl,
            _unbatch1(dkc_b), _unbatch1(dvc_b), k_next)


@partial(jax.jit,
         static_argnames=("spec_k", "tn", "te", "tm", "dn", "de", "dm",
                          "top_k", "use_top_p", "tp_axis", "tp_world"),
         donate_argnums=(2, 3, 4, 5))
def _pool_spec_step(t_params, d_params, kc, vc, dkc, dvc, toks, pos,
                    live, keys, temps, top_p, spec_k, tn, te, tm,
                    dn, de, dm, top_k, use_top_p, tp_axis=None,
                    tp_world=1):
    """Advance EVERY slot one speculative chunk (the per-slot math is
    :func:`_spec_row`).  Arenas (target AND draft) are donated and
    update in place; dead slots run the same math on clamped inputs,
    their rows are garbage the next admission's full-row write
    overwrites, and rows a REJECTED proposal wrote past the accept
    point are overwritten by the next chunk's contiguous write before
    the position mask can ever read them live (the free-rollback
    argument from gpt2_decode._spec_row).  Returns ``(out (S, spec_k)
    candidate tokens, a_draft (S,) accepted-proposal counts, kc, vc,
    dkc, dvc, new_keys)`` — the host emits ``a_draft + 1`` tokens per
    live slot (capped by the request's remaining budget)."""

    def row(kc_r, vc_r, dkc_r, dvc_r, tok, pos_r, live_r, key, temp):
        return _spec_row(t_params, d_params, kc_r, vc_r, dkc_r, dvc_r,
                         tok, pos_r, live_r, key, temp, top_p, spec_k,
                         tn, te, tm, dn, de, dm, top_k, use_top_p,
                         tp_axis=tp_axis, tp_world=tp_world)

    return jax.vmap(row, in_axes=(1, 1, 1, 1, 0, 0, 0, 0, 0),
                    out_axes=(0, 0, 1, 1, 1, 1, 0))(
        kc, vc, dkc, dvc, toks, pos, live, keys, temps)


@jax.jit
def _take_rows(a, idx):
    """Jitted row gather — the compacted paged dispatch's key-table
    select.  One jitted call instead of an eager op: eager jnp
    dispatches carry ~2-3x the per-call overhead, which is real money
    on the per-step path."""
    return jnp.take(a, idx, axis=0)


@jax.jit
def _set_rows(a, idx, vals):
    """Jitted row scatter (key-table write-back) — same eager-op
    avoidance as :func:`_take_rows`."""
    return a.at[idx].set(vals)


@jax.jit
def _merge_keys(keys_tbl, keys_b, idxs, rs):
    """One-dispatch key flush for a batched admission pass: rows
    ``rs`` of the pass's carried keys land at slots ``idxs``."""
    return keys_tbl.at[idxs].set(jnp.take(keys_b, rs, axis=0))


@partial(jax.jit, donate_argnums=(0, 1))
def _write_slot(kc_arena, vc_arena, kc_row, vc_row, slot):
    """Install an admitted request's prefilled cache rows at ``slot``
    (traced index — one executable for every slot).  Arenas/rows are
    pytrees: dense arrays, or (values, scales) tuples for int8 arenas
    — the scales leaf lacks the trailing D axis, so the start index is
    sized per leaf."""
    def wr(arena, row):
        start = (0, slot) + (0,) * (arena.ndim - 2)
        return jax.lax.dynamic_update_slice(arena, row, start)

    return (jax.tree.map(wr, kc_arena, kc_row),
            jax.tree.map(wr, vc_arena, vc_row))


class _LocalExec:
    """The engine's default (single-device) executor: every dispatch
    the engine makes goes through this surface, so the TP backend
    (serve/tp.py ``TPExecutor``) can plug sharded twins in its place
    without the host-side step loop knowing.  Methods bind the
    engine's statics onto the module-level jitted executables — the
    paged pool steps keep their AOT cost-capture dispatch."""

    def __init__(self, eng):
        self._e = eng
        self._aot_memo = {}   # (name, width) -> full AOT cache key

    def pool_decode_step(self, params, kc, vc, toks, pos, live, keys,
                         temps, top_p):
        return _pool_decode_step(params, kc, vc, toks, pos, live,
                                 keys, temps, top_p,
                                 **self._e._statics)

    def pool_spec_step(self, t_params, d_params, kc, vc, dkc, dvc,
                       toks, pos, live, keys, temps, top_p):
        e = self._e
        st = e._statics
        return _pool_spec_step(t_params, d_params, kc, vc, dkc, dvc,
                               toks, pos, live, keys, temps, top_p,
                               spec_k=e.spec_k, tn=st["n_head"],
                               te=st["eps"], tm=st["moe_top_k"],
                               dn=e._d_statics[0], de=e._d_statics[1],
                               dm=e._d_statics[2], top_k=st["top_k"],
                               use_top_p=st["use_top_p"])

    def paged_decode_step(self, params, pool_k, pool_v, tables, toks,
                          pos, live, keys, temps, top_p, block,
                          kernel="block", masks=None, with_lp=False):
        name, fn = (("paged_decode_kernel", _paged_decode_kernel)
                    if kernel == "block"
                    else ("paged_decode_step", _paged_decode_step))
        extra = ({"window": self._e._window} if kernel == "block"
                 else {})  # gather path is refused for windowed models
        return _aot_call(name, fn,
                         params, pool_k, pool_v, tables, toks, pos,
                         live, keys, temps, top_p, masks, block=block,
                         _memo=self._aot_memo,
                         _token=(name, toks.shape[0],
                                 masks is not None, with_lp),
                         with_lp=with_lp,
                         **self._e._statics, **extra)

    def paged_spec_step(self, t_params, d_params, pool_k, pool_v, dkc,
                        dvc, tables, toks, pos, live, keys, temps,
                        top_p, block, kernel="block"):
        e = self._e
        st = e._statics
        name, fn = (("paged_spec_kernel", _paged_spec_kernel)
                    if kernel == "block"
                    else ("paged_spec_step", _paged_spec_step))
        extra = ({"window": e._window} if kernel == "block" else {})
        return _aot_call(name, fn,
                         t_params, d_params, pool_k, pool_v, dkc, dvc,
                         tables, toks, pos, live, keys, temps, top_p,
                         _memo=self._aot_memo,
                         _token=(name, toks.shape[0]),
                         **extra,
                         block=block, spec_k=e.spec_k,
                         tn=st["n_head"], te=st["eps"],
                         tm=st["moe_top_k"], dn=e._d_statics[0],
                         de=e._d_statics[1], dm=e._d_statics[2],
                         top_k=st["top_k"],
                         use_top_p=st["use_top_p"])

    def prefill_one(self, params, ids, prompt_len, key, temp, top_p,
                    mask=None):
        e = self._e
        return _prefill_one(params, ids, prompt_len, key, temp, top_p,
                            **e._statics, quant=e._quant,
                            window=e._window, mask=mask)

    def prefill_batch(self, params, ids, plens, seeds, temps, top_p):
        e = self._e
        return _prefill_batch(params, ids, plens, seeds, temps,
                              top_p, **e._statics, quant=e._quant,
                              window=e._window)

    def chunk_row(self, params, ids, kc_row, vc_row, off):
        return _chunk_row(params, ids, kc_row, vc_row, off,
                          **self._e._chunk_statics)

    def write_slot(self, kc, vc, kc_row, vc_row, slot):
        return _write_slot(kc, vc, kc_row, vc_row, slot)

    def read_slot(self, kc, vc, slot):
        return _read_slot(kc, vc, slot)


class _ProfExec:
    """The step-anatomy hook at the executor seam: every dispatch the
    engine makes routes through ``self._x``, so wrapping HERE times
    dispatch (host) and dispatch→``block_until_ready`` (device) for
    every parallelism mode — ``_LocalExec`` and the tp/ep/pp sharded
    executors alike — without the step loop knowing.  Disabled cost is
    one module-flag read per dispatch (the ``trace._active``
    discipline); with the profiler ON the only added work is a
    ``block_until_ready`` on outputs the engine was about to sync
    anyway, so nothing enters jitted code and the recompile pin
    holds."""

    __slots__ = ("_inner",)

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        # non-dispatch surface (executor-specific attrs) falls through
        return getattr(self._inner, name)

    def pool_decode_step(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.pool_decode_step(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.pool_decode_step,
                                        a, kw)

    def pool_spec_step(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.pool_spec_step(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.pool_spec_step,
                                        a, kw)

    def paged_decode_step(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.paged_decode_step(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.paged_decode_step,
                                        a, kw)

    def paged_spec_step(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.paged_spec_step(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.paged_spec_step,
                                        a, kw)

    def prefill_one(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.prefill_one(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.prefill_one, a, kw)

    def prefill_batch(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.prefill_batch(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.prefill_batch,
                                        a, kw)

    def chunk_row(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.chunk_row(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.chunk_row, a, kw)

    def write_slot(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.write_slot(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.write_slot, a, kw)

    def read_slot(self, *a, **kw):
        if not _stepprof._active:
            return self._inner.read_slot(*a, **kw)
        return _stepprof.timed_dispatch(self._inner.read_slot, a, kw)


class _Slot:
    """Host-side bookkeeping for one pool row (the decode position
    lives in the engine's per-slot arrays — the jitted step's
    inputs — not here).  On a paged engine ``blocks`` is the slot's
    block table (pool block ids, grown block-by-block as decode
    advances) and ``n_shared`` the count of leading blocks REFERENCED
    from the prefix cache (never written, never freed by this slot —
    only released).

    Fork-round fields: ``group`` ties sibling branches of one fork
    family together (None for plain requests — it also gates the
    per-step logprob output that feeds ``score``, the best-of-n
    ranking signal), ``branch`` is this slot's index in the family,
    and ``cow`` marks a slot whose tail blocks MAY still be shared
    with a sibling (the growth pass copy-on-first-writes them).
    ``automaton``/``astate`` carry a structured request's grammar
    state between steps (serve/structured.py)."""

    __slots__ = ("handle", "emitted", "remaining",
                 "first_token_time", "admit_time", "admitted_step",
                 "prefix_nodes", "blocks", "n_shared",
                 "group", "branch", "score", "cow",
                 "automaton", "astate")

    def __init__(self, handle, max_new, now, step):
        self.handle = handle
        self.emitted = []
        self.remaining = max_new
        self.first_token_time = None
        self.admit_time = now
        self.admitted_step = step
        self.prefix_nodes = []   # cached-prefix refs held while live
        self.blocks = []         # paged mode: the slot's block table
        self.n_shared = 0        # leading blocks shared with the cache
        self.group = None        # fork family id (None = plain)
        self.branch = 0          # branch index within the family
        self.score = 0.0         # cumulative chosen-token logprob
        self.cow = False         # tail blocks may be sibling-shared
        self.automaton = None    # structured-decoding grammar
        self.astate = None       # its current state


class _Prefilling:
    """Host-side state of one IN-FLIGHT chunked-prefill admission
    (the ``PagedConfig(prefill_token_budget=)`` path): the request
    holds a reserved slot index and its pool blocks, but its cache
    rows live in a private device row (``kc_row``/``vc_row``) that
    block-width ``_chunk_row`` windows advance across STEPS — only
    when the last chunk lands does the first token sample, the row
    scatter into the blocks, and the slot go live.  Nothing has
    streamed, so an engine failure mid-prefill rejects these
    requeue-safe (``started=False``) and returns their blocks to the
    free list."""

    __slots__ = ("handle", "request", "ids_j", "kc_row", "vc_row",
                 "hidden", "off", "last_off", "blocks", "n_shared",
                 "nodes", "key0", "temp", "t_admit", "admitted_step",
                 "seq")


class _PrefixJob:
    """Host-side state of one fleet-driven PREFILL-FOR-SHIP build (the
    disaggregation round): the shippable canonical-KV prefix of a
    prompt — its ``(plen - 1) // block_size`` full blocks, exactly
    what a warm admission can consume — advanced across steps in
    block-width ``_chunk_row`` windows against a private device row.
    No slot is reserved, no token is sampled, and nothing streams:
    the build is pure cache work, so a failed or abandoned build is
    always replayable from scratch with byte-identical results.
    ``engine`` pins the generation — a supervisor rebuild invalidates
    the job (its row belongs to the dead engine's params) and the
    fleet restarts the build."""

    __slots__ = ("tokens", "plen", "n_goal", "ids_j", "kc_row",
                 "vc_row", "off", "last_off", "nodes", "engine",
                 "hit")


class _Swapped:
    """A preempted request's complete host-side state: byte copies of
    its target cache lanes (and draft rows on a speculative engine),
    the sampling-key chain, and every scrap of slot bookkeeping — so a
    resume continues the EXACT token stream the uninterrupted run
    would have produced.  Swapped requests are STARTED (the admission
    token always streamed), so they are never requeue-safe: an engine
    failure rejects them typed with ``started=True``."""

    __slots__ = ("handle", "request", "emitted", "remaining",
                 "first_token_time", "admit_time", "admitted_step",
                 "pos", "tok", "temp", "key", "image", "dkc_h",
                 "dvc_h", "n_data", "seq", "t_preempt", "j_lo",
                 "group", "branch", "score", "automaton", "astate")

    @property
    def priority(self):
        return getattr(self.request, "priority", 0)


class InferenceEngine:
    """In-process continuous-batching engine for a ``GPT2LMHead``.

    >>> eng = model.serve(max_slots=8)
    >>> h = eng.submit(GenerationRequest(prompt, max_new_tokens=32))
    >>> eng.run_until_complete()
    >>> h.result().tokens      # == model.generate(prompt, ...) exactly

    ``max_len`` defaults to ``cfg.n_positions`` — the same padded width
    single-prompt ``generate`` uses, which is what makes engine logits
    (and therefore tokens) identical to the offline path.  ``top_k``/
    ``top_p`` are ENGINE-level statics (one executable for the pool);
    per-request knobs are temperature/seed/max_new_tokens/deadline.
    ``clock`` is injectable for deterministic scheduling tests.
    ``slo``: optional :class:`~singa_tpu.observe.health.SLO` — retires
    and scheduling passes are checked against it (see
    ``EngineStats``/docs/SERVING.md).

    Fast-decode knobs (docs/SERVING.md "Fast decode"):
    ``cache_dtype="int8"`` quantizes the KV arena (~2× less cache
    traffic, streams byte-identical to offline int8 generate);
    ``draft_model=`` + ``spec_k=`` turn on speculative decoding — up
    to ``spec_k`` tokens per step, greedy streams byte-identical to
    the non-speculative engine, sampled traffic served through
    rejection sampling.  Incompatible combinations (vocab/position
    mismatch, sliding-window draft, spec_k wider than a paged block)
    are rejected with typed errors at construction, never inside a
    jitted dispatch.

    Paged KV (``paged=`` a :class:`~singa_tpu.serve.paged.PagedConfig`;
    docs/SERVING.md "Paged KV and preemption"): the worst-case
    ``(max_slots, max_len)`` slot arena is replaced by ONE block pool
    shared with the prefix cache — admission is bounded by blocks
    free rather than slots free, a request's KV grows block-by-block,
    retire donation is zero-copy adoption, and when the pool runs out
    the engine PREEMPTS (swap a lower-priority request's blocks to
    host, resume byte-identically later) instead of stalling.  Pair
    with ``scheduler="priority"`` so urgent arrivals overtake and
    preempt background work.  Decode runs the BLOCK-NATIVE
    online-softmax kernel by default (``PagedConfig.kernel``),
    admissions prefill at narrow widths and batch per scheduling
    pass, and the pool step dispatches at a compacted width covering
    only the live slots — token streams stay identical to the slot
    engine's (bitwise under ``kernel="gather"``; token-identical
    with an allclose logits pin under the kernel — docs/SERVING.md
    "Paged KV and preemption" has the full pin taxonomy)."""

    def __init__(self, model, max_slots=8, max_len=None, dtype=None,
                 scheduler=None, top_k=0, top_p=None,
                 clock=time.monotonic, slo=None, prefix_cache=None,
                 draft_model=None, spec_k=None, cache_dtype=None,
                 paged=None, tp=None, ep=None, pp=None):
        cfg = model.cfg
        # sliding-window models serve in PAGED mode only (the
        # long-context round): block tables are position-indexed, so
        # a windowed slot drops fully-out-of-window blocks back to
        # the free list as ``pos`` advances — long chats hold
        # O(window) blocks instead of O(length).  The slot arena's
        # worst-case rows still cannot roll, so windowed WITHOUT
        # paged= stays refused, as does the "gather" parity kernel
        # (it materializes the whole row and would attend freed
        # blocks) — both checked below once the paged config parses.
        self._window = _norm_window(cfg)
        if self._window is not None and (paged is None
                                         or paged is False):
            raise NotImplementedError(
                "serve engine supports sliding-window models only in "
                f"paged mode (attn_window={cfg.attn_window}): pass "
                "paged=PagedConfig(...) for windowed decode in "
                "O(window) blocks (docs/SERVING.md 'Long-context "
                "serving'); without paged= the slot arena's "
                "position-indexed rows cannot roll — offline "
                "windowed GPT2LMHead.generate covers the no-engine "
                "case")
        if self._window is not None:
            # the remaining windowed composition limits, checked
            # BEFORE any registry/arena state exists so a refused
            # construction leaks nothing
            _pk = (paged.kernel if isinstance(paged, PagedConfig)
                   else paged.get("kernel", "block")
                   if isinstance(paged, dict) else "block")
            if _pk != "block":
                raise ValueError(
                    f"sliding-window serving requires "
                    f"PagedConfig(kernel='block'), got {_pk!r}: the "
                    f"gather oracle materializes the full row and "
                    f"would attend blocks the windowed slot already "
                    f"dropped")
            if prefix_cache is not None and prefix_cache is not False:
                raise NotImplementedError(
                    "prefix_cache on a sliding-window model: windowed "
                    "slots drop out-of-window blocks, so a retiring "
                    "request's prompt chain is no longer a contiguous "
                    "block prefix the radix tree could adopt; serve "
                    "windowed models without a prefix cache "
                    "(docs/SERVING.md 'Long-context serving' "
                    "composition matrix)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.model = model
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.max_len = int(max_len or cfg.n_positions)
        if self.max_len > cfg.n_positions:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds n_positions "
                f"({cfg.n_positions})")
        if top_k and top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        self._top_k = min(int(top_k or 0), cfg.vocab_size)
        self._top_p = jnp.float32(1.0 if top_p is None else top_p)
        self._use_top_p = top_p is not None
        # -- fast-decode config (speculative + int8 KV, perf round) --
        # every incompatible combination is rejected HERE with a typed
        # error naming the conflict, never deep inside a jitted
        # dispatch where the failure surfaces as a shape/dtype trace
        self._quant = _quant_flag(cache_dtype)   # bool; rejects typos
        self.cache_dtype = cache_dtype
        if spec_k is not None and draft_model is None:
            raise ValueError(
                f"spec_k={spec_k} without draft_model: speculative "
                "decoding needs a draft to propose; pass draft_model= "
                "(or drop spec_k)")
        self.draft = draft_model
        self.spec_k = 4 if spec_k is None else int(spec_k)
        if draft_model is not None:
            dcfg = draft_model.cfg
            if self.spec_k < 2:
                raise ValueError(
                    f"spec_k must be >= 2, got {self.spec_k} (one "
                    "proposal + the bonus token is the smallest chunk)")
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft/target vocab mismatch: draft "
                    f"{dcfg.vocab_size} vs target {cfg.vocab_size} — "
                    "the draft must propose from the target's token "
                    "space")
            if dcfg.n_positions < self.max_len:
                raise ValueError(
                    f"draft n_positions ({dcfg.n_positions}) < engine "
                    f"max_len ({self.max_len}): the draft cache must "
                    "cover every arena position the target can reach")
            if _norm_window(dcfg) is not None:
                raise NotImplementedError(
                    "speculative serve does not support sliding-window "
                    f"drafts (attn_window={dcfg.attn_window}); same "
                    "rolling-cache restriction as the target")
        # ring-prefill composition limits (TPConfig(ring_prefill=)),
        # checked BEFORE any registry/executor/arena state exists so
        # a refused construction leaks nothing; the tp branch below
        # re-coerces idempotently
        if tp is not None and tp is not False:
            from .tp import as_tp_config
            tp = as_tp_config(tp)
            if tp.tp > 1 and tp.ring_prefill:
                if paged is None or paged is False:
                    raise ValueError(
                        "ring_prefill requires paged= (the ring twin "
                        "scatters narrow block-multiple rows; the "
                        "slot arena's full-width write path is not "
                        "wired)")
                if prefix_cache is not None \
                        and prefix_cache is not False:
                    raise ValueError(
                        "ring_prefill with a prefix_cache: ring "
                        "attention reorders the float reduction, so "
                        "its K/V is not byte-canonical with chunked "
                        "prefill — donated blocks would poison the "
                        "cache's warm==cold byte-identity contract")
                if self._window is not None:
                    raise NotImplementedError(
                        "ring_prefill on a sliding-window model is "
                        "not implemented (the ring's causal skip has "
                        "no banded variant here); windowed long "
                        "prompts admit through the chunked-prefill "
                        "budget instead")
                if self._quant:
                    raise ValueError(
                        "ring_prefill with cache_dtype='int8': the "
                        "engine's int8 parity pin is byte equality "
                        "with the offline oracle, which ring "
                        "reduction reordering cannot keep through "
                        "quantization bins; serve int8 without ring")
        # -- expert-parallel / pipeline-parallel backends (serve/ep.py
        # and serve/pp.py): the FULL refusal matrix runs HERE, before
        # EngineStats (or any executor) registers a single metric — a
        # refused construction must leak nothing (the PR-12 leaked-
        # gauge hazard, audited for every ep/pp combination)
        self._ep_cfg = self._pp_cfg = None
        if ep is not None and ep is not False:
            from .ep import as_ep_config
            ep = as_ep_config(ep)
            if ep.ep * ep.tp > 1:
                self._ep_cfg = ep
        if pp is not None and pp is not False:
            from .pp import as_pp_config
            pp = as_pp_config(pp)
            if pp.stages > 1:
                self._pp_cfg = pp
        # conflicts test ACTIVE backends, not knobs-passed: explicit
        # "off" values (tp=1, pp=1, ep=1) next to an active backend
        # are legal no-ops, matching each knob's own "1 = off"
        # contract (tp was coerced to a TPConfig up top when set)
        _tp_on = (tp is not None and tp is not False and tp.tp > 1)
        if self._ep_cfg is not None:
            if _tp_on:
                raise ValueError(
                    "ep= together with tp=: EPConfig carries the "
                    "dense layers' tensor-parallel width itself — "
                    "pass ep=EPConfig(ep=, tp=) and drop the bare "
                    "tp= knob")
            if self._pp_cfg is not None:
                raise ValueError(
                    "ep= together with pp=: one sharded executor "
                    "per engine — serve expert-parallel (ep=) or "
                    "pipeline-parallel (pp=), not both")
            from .ep import check_ep
            check_ep(self._ep_cfg, cfg,
                     model_plan=getattr(model, "plan", None),
                     prefix_cache=prefix_cache)
        if self._pp_cfg is not None:
            if _tp_on:
                raise ValueError(
                    "pp= together with tp=: one sharded executor "
                    "per engine — interleaving tensor parallelism "
                    "inside a stage is the documented next "
                    "extension, not a supported composition")
            from .pp import check_pp
            check_pp(self._pp_cfg, cfg,
                     model_plan=getattr(model, "plan", None),
                     paged=paged, draft_model=draft_model,
                     window=self._window)
        self._clock = clock
        # string schedulers construct PER ENGINE — an object instance
        # forwarded through supervisor/fleet engine_kw would be SHARED
        # across replicas, which is never what "priority scheduling on
        # a fleet" means
        if scheduler == "priority":
            scheduler = PriorityScheduler()
        elif scheduler == "fifo":
            scheduler = FIFOScheduler()
        elif isinstance(scheduler, str):
            raise ValueError(
                f"unknown scheduler {scheduler!r}: pass 'fifo', "
                f"'priority', or a scheduler instance")
        self.scheduler = scheduler or FIFOScheduler()
        self.stats = EngineStats(self.max_slots, clock, slo=slo,
                                 spec=draft_model is not None)
        # per-ENGINE watchdog source: with a shared "serve" source a
        # wedged engine would be masked as long as any sibling engine
        # kept beating (per-tenant engines are a supported pattern)
        self._hb_source = "serve.e" + self.stats.engine_label
        self._log = get_channel("serve")

        model.eval()
        self._params = extract_params(model, dtype=dtype)
        self._statics = dict(
            n_head=cfg.n_head, eps=float(cfg.layer_norm_eps),
            moe_top_k=int(getattr(cfg, "moe_top_k", 2) or 2),
            top_k=self._top_k, use_top_p=self._use_top_p)
        # -- tensor-parallel backend (serve/tp.py): shard the decode
        # math + every KV arena over a `tp` mesh axis.  The executor
        # re-places the extracted weights Megatron-style and supplies
        # sharded twins for every dispatch below; the host-side step
        # loop, paging, prefix cache, and ledger see a single logical
        # engine either way (self._x is the pluggable dispatch seam)
        self.tp_exec = None
        self._tp_cfg = None
        host_params = None
        if tp is not None and tp is not False:
            from .tp import TPExecutor, as_tp_config
            tp = as_tp_config(tp)
            self._tp_cfg = tp
            if tp.tp > 1:
                self.tp_exec = TPExecutor(
                    tp, cfg, statics=self._statics, quant=self._quant,
                    model_plan=getattr(model, "plan", None),
                    engine_label=self.stats.engine_label,
                    reg=self.stats.registry)
                self.tp_exec.set_window(self._window)
                # ring prefill keeps a REPLICATED full-weight copy
                # (context parallelism over the same mesh: sequence
                # sharded, weights whole) — grab the host tree before
                # the Megatron placement below consumes it; the ring
                # composition checks run once paged/prefix parse
                host_params = (self._params
                               if getattr(tp, "ring_prefill", False)
                               else None)
                self._params = self.tp_exec.place_params(self._params)
                self.stats.tp_source = self.tp_exec.snapshot
        # -- expert-parallel / pipeline-parallel executors: same seam,
        # different mesh.  Validation already ran up top (before any
        # registration); the executors re-check defensively before
        # registering their own metrics.
        self.ep_exec = self.pp_exec = None
        if self._ep_cfg is not None:
            from .ep import EPExecutor
            self.ep_exec = EPExecutor(
                self._ep_cfg, cfg, statics=self._statics,
                quant=self._quant,
                model_plan=getattr(model, "plan", None),
                engine_label=self.stats.engine_label,
                reg=self.stats.registry, prefix_cache=prefix_cache)
            self.ep_exec.set_window(self._window)
            self._params = self.ep_exec.place_params(self._params)
            self.stats.ep_source = self.ep_exec.snapshot
        if self._pp_cfg is not None:
            from .pp import PPExecutor
            self.pp_exec = PPExecutor(
                self._pp_cfg, cfg, statics=self._statics,
                quant=self._quant,
                model_plan=getattr(model, "plan", None),
                engine_label=self.stats.engine_label,
                reg=self.stats.registry)
            self._params = self.pp_exec.place_params(self._params)
            self.stats.pp_source = self.pp_exec.snapshot
        #: the ONE sharded executor (tp | ep | pp | None) — placement
        #: and late-statics calls below go through this seam so the
        #: host-side step loop never knows which mesh it runs over
        self._shard = (self.tp_exec or self.ep_exec or self.pp_exec)
        # the step-anatomy shim wraps the seam permanently: one
        # module-flag read per dispatch when the profiler is off
        # (observe/stepprof.py), dispatch/ready timestamps when on
        self._x = _ProfExec(self._shard if self._shard is not None
                            else _LocalExec(self))
        # fixed-shape KV arena keyed on (max_slots, max_len): L layers,
        # H_kv heads (GQA keeps the narrow cache), compute dtype —
        # or (int8 values, f32 scales) tuples for cache_dtype="int8"
        # (half the bytes per element on a cache-read-bound loop; the
        # same (values, scales) layout gpt2_decode._quantize_kv makes)
        L, S, W = cfg.n_layer, self.max_slots, self.max_len
        H_kv = cfg.n_kv_head
        D = cfg.n_embd // cfg.n_head
        cdt = self._params["wte"].dtype

        def _arena(L_, H_, D_, shard=True):
            if self._quant:
                z = (jnp.zeros((L_, S, H_, W, D_), jnp.int8),
                     jnp.zeros((L_, S, H_, W), jnp.float32))
            else:
                z = jnp.zeros((L_, S, H_, W, D_), cdt)
            if self._shard is None:
                return z
            # target arenas shard on the H_kv axis; the DRAFT arena
            # (shard=False) replicates — every shard runs the full
            # draft, which is what keeps any draft geometry legal
            return (self._shard.place_cache(z) if shard
                    else self._shard.place_replicated(z))

        # -- paged KV mode (serve/paged.py): ONE block pool replaces
        # the per-slot worst-case arena; capacity becomes "blocks
        # free", requests grow block-by-block, and preemption/swap +
        # the unified prefix cache ride the same pool.  max_slots
        # still bounds the decode vmap width, but a slot costs only
        # the blocks its request actually holds
        self.paged_arena = None
        self._spec_pad = 0 if draft_model is None else self.spec_k - 1
        if paged is not None and paged is not False:
            if paged is True:
                paged = PagedConfig()
            elif isinstance(paged, dict):
                paged = PagedConfig(**paged)
            if not isinstance(paged, PagedConfig):
                raise ValueError(
                    f"paged must be a PagedConfig, a kwargs dict, or "
                    f"True, got {type(paged)}")
            if self.max_len % paged.block_size != 0:
                raise ValueError(
                    f"max_len ({self.max_len}) must be a multiple of "
                    f"the paged block_size ({paged.block_size}) so "
                    f"block tables tile the row exactly")
            if draft_model is not None \
                    and self.spec_k > paged.block_size:
                raise ValueError(
                    f"spec_k ({self.spec_k}) > paged block_size "
                    f"({paged.block_size}): a verify chunk would span "
                    f"more than two pool blocks; raise block_size or "
                    f"lower spec_k")
            self.paged_arena = PagedKVArena(
                paged, L, H_kv, D, cdt, row_width=W,
                quant=self._quant,
                engine_label=self.stats.engine_label,
                reg=self.stats.registry, tp=self._shard)
            self.stats.paged_source = self.paged_arena.snapshot
            self._kc = self._vc = None
        else:
            self._kc = _arena(L, H_kv, D)
            self._vc = _arena(L, H_kv, D)
        # draft-side state (speculative decoding): its own params and
        # its own (cheap) KV arena, advanced in lockstep by the spec
        # pool step
        self._d_params = self._d_statics = None
        self._dkc = self._dvc = None
        if self.draft is not None:
            self.draft.eval()
            self._d_params = extract_params(self.draft, dtype=dtype)
            dcfg = self.draft.cfg
            self._d_statics = (dcfg.n_head, float(dcfg.layer_norm_eps),
                               int(getattr(dcfg, "moe_top_k", 2) or 2))
            self._dkc = _arena(dcfg.n_layer, dcfg.n_kv_head,
                               dcfg.n_embd // dcfg.n_head, shard=False)
            self._dvc = _arena(dcfg.n_layer, dcfg.n_kv_head,
                               dcfg.n_embd // dcfg.n_head, shard=False)
            if self._shard is not None:
                self._d_params = self._shard.place_replicated(
                    self._d_params)
                self._shard.set_spec(self.spec_k, self._d_statics)
        # per-slot host state + device sampling keys
        self._slots = [None] * S            # _Slot or None
        self._toks = np.zeros(S, np.int32)  # last emitted token
        self._pos = np.zeros(S, np.int32)
        self._temps = np.zeros(S, np.float32)
        self._keys = jnp.zeros((S, 2), jnp.uint32)
        if self._shard is not None:
            # committed replicated so the sharded twins never pay a
            # per-dispatch broadcast for the key table
            self._keys = self._shard.place_replicated(self._keys)
        self._handles = {}
        self._swapped = []                  # paged mode: _Swapped list
        # batched-admission deferral (the gather-tax round): one
        # scheduling pass's prefilled rows (_prefill_admissions) plus
        # the per-request scatter/key writes deferred onto them —
        # flushed as ONE pool scatter + ONE key write per pass
        self._admit_batch = None            # (keys, kc, vc) device
        self._pending_scatter = []          # [(batch row, lanes dict)]
        self._pending_keys = []             # [(slot idx, batch row)]
        self._batch_cache = None            # last pass's pure batch
        self._swap_seq = itertools.count()
        self._closed = False
        self._failed = False
        self.step_count = 0
        # radix prefix cache (serve/prefix.py): block-granular KV
        # reuse for shared prompts and pinned sessions.  The cache is
        # engine-owned and starts empty — a supervisor rebuild gets a
        # fresh one (cold but correct) from the forwarded config.
        self.prefix_cache = None
        self._sched_cost = None
        self._chunk_statics = None
        # identity check, not truthiness: prefix_cache={} means
        # "enable with defaults", and silently disabling on a falsy
        # dict would only surface as stats["prefix"] == None much later
        if prefix_cache is not None and prefix_cache is not False:
            if prefix_cache is True:
                prefix_cache = PrefixCacheConfig()
            elif isinstance(prefix_cache, dict):
                prefix_cache = PrefixCacheConfig(**prefix_cache)
            if not isinstance(prefix_cache, PrefixCacheConfig):
                raise ValueError(
                    f"prefix_cache must be a PrefixCacheConfig, a "
                    f"kwargs dict, or True, got {type(prefix_cache)}")
            # int8 + prefix cache is SUPPORTED since the paged round:
            # the block pool is pytree-leaf-generic ((values, scales)
            # blocks), and quantized engines with a cache route EVERY
            # admission through the chunked prefill path so warm and
            # cold streams stay byte-identical to each other (see
            # _admit; docs/SERVING.md "int8 and the prefix cache")
            if self.paged_arena is not None:
                # one pool, one granularity: the radix tree shares the
                # paged arena's blocks by reference, so its block size
                # IS the arena's
                if prefix_cache.block_size != \
                        self.paged_arena.block_size:
                    raise ValueError(
                        f"prefix_cache.block_size "
                        f"({prefix_cache.block_size}) != paged "
                        f"block_size ({self.paged_arena.block_size}): "
                        f"a paged engine keeps ONE block pool, so the "
                        f"cache must share its granularity (its "
                        f"num_blocks is ignored — capacity is the "
                        f"arena's)")
            elif self.max_len % prefix_cache.block_size != 0:
                raise ValueError(
                    f"max_len ({self.max_len}) must be a multiple of "
                    f"prefix_cache.block_size "
                    f"({prefix_cache.block_size}) so chunked prefill "
                    f"windows never cross the arena edge")
            self.prefix_cache = PrefixCache(
                prefix_cache, L, H_kv, D, cdt,
                engine_label=self.stats.engine_label,
                reg=self.stats.registry, quant=self._quant,
                arena=self.paged_arena, tp=self._shard)
            self.prefix_cache.attach_row_geometry(W)
            if self.paged_arena is not None:
                # cached-but-unreferenced blocks are soft free space:
                # allocation evicts LRU leaves before failing
                self.paged_arena.evict_cb = \
                    self.prefix_cache._evict_one
            self._chunk_statics = dict(
                n_head=cfg.n_head, eps=float(cfg.layer_norm_eps),
                moe_top_k=self._statics["moe_top_k"],
                chunk=prefix_cache.block_size, window=self._window)
            if self._shard is not None:
                self._shard.set_chunk(self._chunk_statics)
            self.stats.prefix_source = self.prefix_cache.snapshot
            # prefill-interleave pricing: warm admissions that
            # recompute at most one chunk don't consume the cold
            # budget (scheduler.schedule's ``cost``; custom schedulers
            # without the parameter keep the flat 1-per-admit price)
            try:
                params_ = inspect.signature(
                    self.scheduler.schedule).parameters
                if "cost" in params_:
                    self._sched_cost = self._prefill_cost
            except (TypeError, ValueError):
                pass
        # -- chunked-prefill token budget (the long-context round):
        # PagedConfig(prefill_token_budget=) splits admissions across
        # steps in block-width _chunk_row windows — host state for the
        # in-flight chunked prefills lives in self._prefilling (slot
        # index -> _Prefilling; the slot is RESERVED but not live, so
        # the decode dispatch never sees it until the first token
        # samples)
        self._budget = (self.paged_arena.config.prefill_token_budget
                        if self.paged_arena is not None else None)
        self._prefilling = {}
        self._prefill_seq = itertools.count()
        self._own_metrics = []
        if self._budget is not None:
            if self._chunk_statics is None:
                self._chunk_statics = dict(
                    n_head=cfg.n_head, eps=float(cfg.layer_norm_eps),
                    moe_top_k=self._statics["moe_top_k"],
                    chunk=self.paged_arena.block_size,
                    window=self._window)
                if self._shard is not None:
                    self._shard.set_chunk(self._chunk_statics)
            self._c_budget_chunks = self.stats.registry.counter(
                "serve.prefill.budget_chunks",
                help="block-width chunk dispatches the chunked-"
                     "prefill token budget split admissions into",
                engine=self.stats.engine_label)
            self._own_metrics.append(self._c_budget_chunks)
        # -- CoW KV forking (serve/fork.py): fork-family id sequence
        # and the fork-round metrics (paged engines only — forking
        # rides on the arena's block refcounts)
        self._fork_seq = itertools.count(1)
        self._c_fork_branches = self._c_fork_cow = None
        self._c_fork_pruned = self._g_fork_shared = None
        if self.paged_arena is not None:
            self._c_fork_branches = self.stats.registry.counter(
                "serve.fork.branches",
                help="decoding branches forked off live slots "
                     "(n>1 admissions and explicit fork() calls)",
                engine=self.stats.engine_label)
            self._c_fork_cow = self.stats.registry.counter(
                "serve.fork.cow_copies",
                help="copy-on-write block copies: a branch reached a "
                     "block a sibling still references and got a "
                     "private copy",
                engine=self.stats.engine_label)
            self._c_fork_pruned = self.stats.registry.counter(
                "serve.fork.pruned",
                help="branches cut by prune() (private blocks freed, "
                     "result sealed finish_reason=pruned)",
                engine=self.stats.engine_label)
            self._g_fork_shared = self.stats.registry.gauge(
                "serve.fork.shared_blocks",
                help="arena blocks currently referenced by more than "
                     "one live slot (each saves a full block of KV "
                     "per extra reference)",
                engine=self.stats.engine_label)
            self._own_metrics.extend([
                self._c_fork_branches, self._c_fork_cow,
                self._c_fork_pruned, self._g_fork_shared])
        # -- ring-attention prefill (TPConfig(ring_prefill=True)):
        # cold long-prompt admissions prefill SEQUENCE-sharded over
        # the tp mesh (parallel/ring_attention.py) — composition was
        # validated up top, before any registration
        self._ring = bool(self.tp_exec is not None and self._tp_cfg
                          and getattr(self._tp_cfg, "ring_prefill",
                                      False))
        if self._ring:
            self.tp_exec.enable_ring(host_params)
        self._log.info(
            "engine up: slots=%d max_len=%d cache_dtype=%s "
            "prefix_cache=%s spec=%s paged=%s tp=%s",
            S, W, cache_dtype or str(cdt),
            "off" if self.prefix_cache is None else
            f"{self.prefix_cache.num_blocks}x"
            f"{self.prefix_cache.block_size}",
            "off" if self.draft is None else f"k={self.spec_k}",
            "off" if self.paged_arena is None else
            f"{self.paged_arena.num_blocks}x"
            f"{self.paged_arena.block_size}",
            "off" if self.tp_exec is None
            else f"{self.tp_exec.tp} shards",
        )
        if self.ep_exec is not None:
            self._log.info(
                "engine ep backend: %d expert shards x %d tp "
                "(capacity_factor=%s)", self.ep_exec.ep,
                self.ep_exec.tp, self.ep_exec.config.capacity_factor)
        if self.pp_exec is not None:
            self._log.info(
                "engine pp backend: %d stages x %d microbatches",
                self.pp_exec.stages, self.pp_exec.microbatches)

    # -- submission ------------------------------------------------------
    def submit(self, request) -> RequestHandle:
        """Queue a request; returns immediately with a handle.  Raises
        QueueFullError under back-pressure and ValueError for requests
        that could never fit the arena."""
        if self._closed:
            raise RuntimeError(
                "engine is closed; build a new one with model.serve()")
        if self._failed:
            raise EngineFailedError(
                "engine has failed; rebuild it (EngineSupervisor does "
                "this automatically)", engine_step=self.step_count)
        if not isinstance(request, GenerationRequest):
            request = GenerationRequest(np.asarray(request))
        self.validate_request(request)
        if request.request_id in self._handles:
            # an in-flight duplicate would orphan the earlier handle
            # (the id is the engine's completion-routing key); finished
            # requests are evicted at retire/reject, so an id may be
            # REUSED once its predecessor resolved
            raise ValueError(
                f"request_id {request.request_id!r} is already "
                f"in flight")
        handle = RequestHandle(request)
        t_sub = self._clock()
        if _reqs._active:
            # request-ledger hook: one flag read when tracing is off.
            # Starts (or, on a supervisor/fleet requeue, CONTINUES)
            # this request's timeline with a hop on this engine
            _reqs._ledger.on_submit(
                request.request_id, engine=self.stats.engine_label,
                t=t_sub, prompt_len=len(request.prompt_ids),
                max_new_tokens=request.max_new_tokens)
        self.stats.on_submit()
        try:
            self.scheduler.enqueue(request)
        except Exception:
            self.stats.on_queue_full(request.request_id)
            _trace.event("serve/request_rejected", cat="serve",
                         request=request.request_id,
                         reason="queue_full")
            if _reqs._active:
                _reqs._ledger.on_reject(
                    request.request_id, t=self._clock(),
                    reason="queue_full",
                    engine=self.stats.engine_label, started=False)
            raise
        handle._submit_time = t_sub
        self._handles[request.request_id] = handle
        if request.n > 1:
            # best-of-n: the scheduler will fork n-1 siblings off this
            # slot the moment the prompt admits (serve/fork.py);
            # surface the n-branch view instead of the bare handle
            handle._fork_children = []
            return ForkHandle(self, handle)
        return handle

    def validate_request(self, request):
        """Submit-time feasibility: raises ValueError for a request
        that could NEVER fit this engine's arena (position space, or
        paged worst-case blocks).  Shared by :meth:`submit` and the
        fleet's disaggregated admission path, so a ship-parked
        request fails the caller synchronously with the same typed
        error a direct submit would."""
        need = len(request.prompt_ids) + request.max_new_tokens
        spec_pad = 0 if self.draft is None else self.spec_k - 1
        if need + spec_pad > self.max_len:
            # speculative engines reserve spec_k - 1 positions of
            # verify-chunk headroom past the last emitted token (the
            # same rule as generate_speculative) — checked HERE so the
            # failure is a submit-time ValueError, not a clipped
            # dynamic_update_slice corrupting a neighbor's rows.
            # max_len is a POSITION-EMBEDDING bound (<= n_positions),
            # not a memory one: within it, the long-context serve
            # path handles long traffic first-class — a chunked-
            # prefill token budget (PagedConfig(prefill_token_budget=)
            # splits a long admission across steps so decode lanes
            # never stall) and, for sliding-window models, windowed
            # paged decode in O(window) blocks.  Only generations
            # whose POSITIONS exceed n_positions remain offline-only
            # (the windowed GPT2LMHead.generate fallback); see
            # docs/SERVING.md "Long-context serving" for what still
            # refuses (windowed without paged=, windowed + prefix
            # cache, windowed + kernel='gather').
            raise ValueError(
                f"prompt ({len(request.prompt_ids)}) + max_new_tokens "
                f"({request.max_new_tokens})"
                + (f" + spec_k-1 ({spec_pad})" if spec_pad else "")
                + f" exceeds the engine arena max_len ({self.max_len})"
                f" — the model's position space, not a memory limit "
                f"(long admissions within it serve via the chunked-"
                f"prefill budget / windowed paged decode; docs/"
                f"SERVING.md 'Long-context serving'); only beyond-"
                f"n_positions generations need the offline windowed "
                f"GPT2LMHead.generate")
        if self.paged_arena is not None:
            B = self.paged_arena.block_size
            worst = ((len(request.prompt_ids) + request.max_new_tokens
                      - 1 + spec_pad) // B) + 1
            if self._window is not None:
                # a windowed slot never holds more than the blocks
                # covering one window span plus the block being
                # written — out-of-window blocks return to the free
                # list as pos advances, so worst-case footprint is
                # O(window), not O(prompt + generation)
                worst = min(worst,
                            (self._window - 1 + spec_pad) // B + 2)
            if worst > self.paged_arena.num_blocks:
                # a request that could never fit the pool ALONE would
                # deadlock the growth loop; fail it at submit, typed
                raise ValueError(
                    f"request needs up to {worst} KV blocks but the "
                    f"paged pool holds {self.paged_arena.num_blocks}; "
                    f"raise PagedConfig.num_blocks or lower "
                    f"max_new_tokens")
        if request.n > 1 or request.structured is not None:
            what = (f"n={request.n}" if request.n > 1
                    else "structured decoding")
            if self.paged_arena is None:
                raise ValueError(
                    f"{what} needs a paged engine (model.serve("
                    f"paged=PagedConfig(...))) — forking rides on the "
                    f"arena's per-block refcounts and structured masks "
                    f"on its per-row dispatch")
            if self.draft is not None:
                raise ValueError(
                    f"{what} is incompatible with speculative decoding "
                    f"(the verify chunk samples several tokens per "
                    f"dispatch; per-token masks and branch logprobs "
                    f"need the one-token step)")
            if self._shard is not None:
                raise ValueError(
                    f"{what} is not supported on the tensor-parallel "
                    f"backend yet (the tp twins predate the mask/"
                    f"logprob dispatch signature)")
        if request.n > 1:
            if self._window is not None:
                raise ValueError(
                    f"n={request.n} on a sliding-window engine: "
                    f"windowed slots DROP out-of-window blocks, which "
                    f"a sibling may still share — fork needs the full "
                    f"block table")
            if self._budget is not None or self._ring:
                raise ValueError(
                    f"n={request.n} with chunked/ring prefill: "
                    f"branches fork off the admission pass, which "
                    f"these paths split across steps; use a plain "
                    f"paged admission for forked requests")
            B = self.paged_arena.block_size
            plen = len(request.prompt_ids)
            shared = plen // B
            tail = (plen + request.max_new_tokens - 1) // B + 1 - shared
            if shared + request.n * tail > self.paged_arena.num_blocks:
                raise ValueError(
                    f"n={request.n} needs up to {shared} shared + "
                    f"{request.n}x{tail} per-branch KV blocks but the "
                    f"paged pool holds {self.paged_arena.num_blocks}; "
                    f"raise PagedConfig.num_blocks, lower n, or lower "
                    f"max_new_tokens")
        if request.structured is not None:
            a = request.structured
            vs = getattr(a, "vocab_size", None)
            if vs is not None and int(vs) != int(self.cfg.vocab_size):
                raise ValueError(
                    f"structured automaton covers vocab_size={vs} but "
                    f"the model's vocab is {self.cfg.vocab_size} — the "
                    f"mask would mis-index logits")
            m0 = np.asarray(a.mask(a.initial()), bool)
            if m0.shape != (int(self.cfg.vocab_size),):
                raise ValueError(
                    f"structured mask shape {m0.shape} != "
                    f"({self.cfg.vocab_size},) — masks must be one "
                    f"bool per vocab token")
            if not m0.any():
                raise ValueError(
                    "structured automaton's initial state accepts NO "
                    "token — the grammar is unsatisfiable under this "
                    "vocab (every legal first emission simulates to a "
                    "dead end)")

    @property
    def pending(self) -> bool:
        """True while any request is queued, occupying a slot,
        mid-chunked-prefill, or swapped out awaiting resume."""
        return (self.scheduler.queue_depth > 0
                or any(s is not None for s in self._slots)
                or bool(self._prefilling)
                or bool(self._swapped))

    def check_block_accounting(self):
        """Leak invariant for the paged arena: every used pool block
        is owned by exactly one of (a) the prefix cache's radix tree,
        (b) a live slot's block table, (c) an in-flight chunked
        prefill.  Anything else is a leaked block — raised as an
        AssertionError naming the counts, so benches and tests can
        assert ``arena.used == cached + live_referenced`` after a
        drain with one call.  Returns the used-block count.  Fork-
        shared blocks are counted ONCE here (ownership is the block
        id, not the refcount) — the arena's refcounts only govern
        when ``free`` actually recycles."""
        arena = self.paged_arena
        if arena is None:
            return 0
        owned = set()
        if self.prefix_cache is not None:
            owned.update(self.prefix_cache.cached_block_ids())
        n_cached = len(owned)
        for s in self._slots:
            if s is not None:
                owned.update(b for b in s.blocks if b != arena.trash)
        for pf in self._prefilling.values():
            owned.update(b for b in pf.blocks if b != arena.trash)
        used = arena.blocks_used
        if used != len(owned):
            raise AssertionError(
                f"paged-arena block leak: arena reports {used} used "
                f"blocks but owners account for {len(owned)} "
                f"({n_cached} cached + {len(owned) - n_cached} "
                f"live/prefilling) — "
                f"{used - len(owned)} block(s) leaked")
        return used

    # -- lifecycle -------------------------------------------------------
    def close(self, force=False):
        """Retire the engine: unregister its ``serve.*{engine=n}``
        metrics from the process-wide observe registry (they would
        otherwise be pinned — TTFT/TPOT value lists included — for
        process lifetime) and drop the KV arena references.  Idempotent;
        the engine must be drained (``not pending``) first unless
        ``force=True`` (the fleet's failover path: an abandoned
        replica's handles are already rejected typed, its device state
        is garbage to be released, not drained).  Also the
        context-manager exit: ``with model.serve(...) as eng: ...``."""
        if self.pending and not force:
            raise RuntimeError(
                f"close() with work in flight (queue="
                f"{self.scheduler.queue_depth}, live={self.live_slots});"
                f" drain with run_until_complete() first")
        if (not force and not self._failed
                and self.paged_arena is not None):
            # leak invariant: a drained engine's arena holds exactly
            # the cache-owned blocks — any extra used block is a leak
            # (a forked branch that freed a shared block, a preempt
            # path that dropped a refcount on the floor)
            self.check_block_accounting()
        self._release_everything()

    def _release_everything(self):
        self.stats.unregister()
        _monitor.forget(self._hb_source)
        _stepprof.forget_engine(self.stats.engine_label)
        if self.prefix_cache is not None:
            self.prefix_cache.unregister()
        if self.paged_arena is not None:
            self.paged_arena.unregister()
        if self.tp_exec is not None:
            self.tp_exec.unregister()
        if self.ep_exec is not None:
            self.ep_exec.unregister()
        if self.pp_exec is not None:
            self.pp_exec.unregister()
        self.stats.registry.remove(*self._own_metrics)
        self._own_metrics = []
        self._kc = self._vc = None
        self._dkc = self._dvc = None
        self._params = self._d_params = None
        self._swapped = []
        self._prefilling = {}
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is None:
            self.close()
        else:
            # don't let the drained-first check mask the in-flight
            # exception; still release the registry entries AND the
            # arena/params (the pinning close() exists to prevent)
            self._release_everything()
        return False

    @property
    def live_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def live_request_ids(self):
        """Request ids currently occupying slots OR swapped out —
        i.e. STARTED: tokens already streamed through ``on_token`` (a
        swapped request streamed at least its admission token), so
        these are never safely re-runnable elsewhere (the fleet's
        failover path uses exactly this distinction)."""
        ids = {s.handle.request.request_id
               for s in self._slots if s is not None}
        ids.update(sw.request.request_id for sw in self._swapped)
        return ids

    # -- the iteration-level step loop -----------------------------------
    def step(self) -> bool:
        """One engine iteration: decode every live slot by one token,
        retire finished rows, then backfill freed slots from the queue
        (so backfill lands on the very step a row retires).  Returns
        ``pending``.

        A raising decode/prefill does NOT wedge the engine: every
        in-flight and queued request is rejected with a typed
        :class:`EngineFailedError` (``started`` says which were
        occupying slots), the engine marks itself failed, and the
        error re-raises for the caller/supervisor — no handle is ever
        left dangling behind a dead pool."""
        if self._closed:
            raise RuntimeError(
                "engine is closed; build a new one with model.serve()")
        if self._failed:
            raise EngineFailedError(
                "engine has failed; rebuild it (EngineSupervisor does "
                "this automatically)", engine_step=self.step_count)
        if _monitor.active():
            # arm BEFORE the dispatches below: if the first prefill or
            # decode after an idle period wedges, this beat is what
            # lets the watchdog see an armed, then-silent source — a
            # re-arm only after the dispatch returns would never come
            _monitor.heartbeat(self._hb_source)
        if _stepprof._active:
            _stepprof.begin(self.stats.engine_label, self.step_count)
        try:
            if self.paged_arena is not None:
                # paged growth: every live slot must own the block(s)
                # the coming decode/spec chunk will write BEFORE the
                # dispatch; a slot that cannot grow (pool exhausted,
                # no strictly-lower-priority victim) swaps ITSELF out
                self._grow_live_slots()
            if any(s is not None for s in self._slots):
                self._decode_once()
            if _stepprof._active:
                _stepprof.push("schedule")
            self._schedule(self._clock())
            if _stepprof._active:
                _stepprof.pop()
        except Exception as e:
            # a raising step has no meaningful anatomy: drop the open
            # record so a later dispatch can't land on a stale state
            _stepprof.abort()
            raise self._fail(e) from e
        self.stats.on_schedule(self.scheduler.queue_depth)
        self.step_count += 1
        if _stepprof._active:
            _stepprof.end()
        pending = self.pending
        if not pending and _monitor.active():
            # drained: refresh liveness but DISARM hang detection —
            # an idle engine between traffic bursts is not a wedged
            # one; the next step's top-of-loop beat re-arms
            _monitor.heartbeat(self._hb_source, busy=False)
        return pending

    def _fail(self, cause) -> EngineFailedError:
        """Fail the engine: reject every in-flight (started=True) and
        queued (started=False) request typed, disarm the watchdog
        source, and return the error for ``step()`` to raise.  The KV
        arena and params stay allocated until ``close()`` — the
        supervisor reads nothing from them, but a debugger might."""
        self._failed = True
        # drop any deferred admission writes FIRST: the teardown loop
        # below frees blocks (whose _free_slot_blocks guard would
        # otherwise re-run the very flush that may have just raised —
        # a second raise mid-loop would abandon the remaining handles,
        # breaking the no-dangling-handle contract), and a failing
        # engine's pool state is garbage to be released, not written
        self._pending_scatter = []
        self._pending_keys = []
        self._admit_batch = None
        self._batch_cache = None
        step = self.step_count
        msg = f"engine failed at step {step}: {cause!r}"
        self._log.error("%s — rejecting %d in-flight and %d queued "
                        "requests typed", msg, self.live_slots,
                        self.scheduler.queue_depth)
        _trace.event("serve/engine_failed", cat="serve", step=step,
                     error=repr(cause), live=self.live_slots,
                     queued=self.scheduler.queue_depth)
        self.stats.registry.counter(
            "resilience.engine_failures",
            help="serve engines failed by a raising decode/prefill").inc()
        t_fail = self._clock()
        lbl = self.stats.engine_label
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._release_prefix(slot)
            self._free_slot_blocks(slot)
            rid = slot.handle.request.request_id
            # typed rejections must be VISIBLE, not just raised: the
            # instant puts the rejected request in the trace/flight
            # recorder and the ledger hook keeps its timeline from
            # vanishing from the request log
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="engine_failed",
                         started=True)
            if _reqs._active:
                _reqs._ledger.on_reject(rid, t=t_fail,
                                        reason="engine_failed",
                                        engine=lbl, started=True)
            slot.handle._reject(EngineFailedError(
                f"{msg} ({rid} was in flight, "
                f"{len(slot.emitted)} tokens emitted)", request_id=rid,
                started=True, engine_step=step))
            self._slots[i] = None
            self._handles.pop(rid, None)
        # mid-chunked-prefill requests (the token-budget path) have
        # streamed NOTHING — their first token samples only when the
        # last chunk lands — so they reject requeue-safe
        # (started=False), and their partially-filled blocks return
        # to the free list HERE: a supervisor restart must find zero
        # leaked blocks behind a fault that fired between chunks
        # (docs/RESILIENCE.md; chaos_longctx gates it)
        for idx, pf in list(self._prefilling.items()):
            rid = pf.request.request_id
            if self.prefix_cache is not None and pf.nodes:
                self.prefix_cache.release(pf.nodes)
            if self.paged_arena is not None and pf.blocks:
                self.paged_arena.free(
                    [b for b in pf.blocks[pf.n_shared:]
                     if b != self.paged_arena.trash])
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="engine_failed",
                         started=False)
            if _reqs._active:
                _reqs._ledger.on_reject(rid, t=t_fail,
                                        reason="engine_failed",
                                        engine=lbl, started=False)
            pf.handle._reject(EngineFailedError(
                f"{msg} ({rid} was mid-chunked-prefill at offset "
                f"{pf.off}, nothing streamed)", request_id=rid,
                started=False, engine_step=step))
            self._handles.pop(rid, None)
        self._prefilling = {}
        # swapped-out requests are STARTED (tokens streamed before the
        # preemption): typed started=True, never requeued — without
        # this pass the generic not-done sweep below would misread
        # them as requeue-safe and a restart would re-stream duplicates
        for sw in self._swapped:
            rid = sw.request.request_id
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="engine_failed",
                         started=True)
            if _reqs._active:
                _reqs._ledger.on_reject(rid, t=t_fail,
                                        reason="engine_failed",
                                        engine=lbl, started=True)
            sw.handle._reject(EngineFailedError(
                f"{msg} ({rid} was swapped out mid-decode, "
                f"{len(sw.emitted)} tokens emitted)", request_id=rid,
                started=True, engine_step=step))
            self._handles.pop(rid, None)
        self._swapped = []
        for req in self.scheduler.drain():
            h = self._handles.pop(req.request_id, None)
            if h is not None:
                _trace.event("serve/request_rejected", cat="serve",
                             request=req.request_id,
                             reason="engine_failed", started=False)
                if _reqs._active:
                    _reqs._ledger.on_reject(req.request_id, t=t_fail,
                                            reason="engine_failed",
                                            engine=lbl, started=False)
                h._reject(EngineFailedError(
                    f"{msg} ({req.request_id} was queued, not started)",
                    request_id=req.request_id, started=False,
                    engine_step=step))
        # a request can also fail MID-ADMISSION: popped from the queue
        # by schedule() but not yet occupying a slot (e.g. a raising
        # prefill or prefix-cache copy).  It has streamed nothing, so
        # it is requeue-safe (started=False) — without this pass its
        # handle would be cleared unresolved and the caller wedged
        for rid, h in list(self._handles.items()):
            if not h.done():
                _trace.event("serve/request_rejected", cat="serve",
                             request=rid, reason="engine_failed",
                             started=False)
                if _reqs._active:
                    _reqs._ledger.on_reject(rid, t=t_fail,
                                            reason="engine_failed",
                                            engine=lbl, started=False)
                h._reject(EngineFailedError(
                    f"{msg} ({rid} was admitting, not started)",
                    request_id=rid, started=False, engine_step=step))
        self._handles.clear()
        if _monitor.active():
            # dead, not hung: liveness beat with hang detection off so
            # the watchdog doesn't page for an engine that failed FAST
            _monitor.heartbeat(self._hb_source, busy=False)
        return EngineFailedError(msg, engine_step=step)

    def shed(self, reason="slo_pressure", below_priority=None):
        """Shed the lowest-priority queued request (see
        ``FIFOScheduler.shed_lowest``), rejecting its handle with a
        typed :class:`LoadShedError`.  Returns the shed request or
        None.  The supervisor's SLO-pressure admission mode calls this
        before latency collapses; direct engine users can too."""
        victim = self.scheduler.shed_lowest(reason,
                                            below_priority=below_priority)
        if victim is None:
            return None
        h = self._handles.pop(victim.request_id, None)
        if h is not None:
            h._reject(LoadShedError(
                f"{victim.request_id} shed ({reason}): priority "
                f"{victim.priority} was the lowest queued under SLO "
                f"pressure"))
        _trace.event("serve/shed", cat="serve", reason=reason,
                     request=victim.request_id,
                     priority=victim.priority)
        _trace.event("serve/request_rejected", cat="serve",
                     request=victim.request_id,
                     reason=f"shed:{reason}")
        if _reqs._active:
            _reqs._ledger.on_reject(victim.request_id, t=self._clock(),
                                    reason=f"shed:{reason}",
                                    engine=self.stats.engine_label,
                                    started=False)
        self._log.warning("shed %s (%s, priority=%d)",
                          victim.request_id, reason, victim.priority)
        return victim

    def run_until_complete(self, max_steps=None):
        """Drive ``step()`` until every submitted request resolves.
        ``max_steps`` guards tests against scheduling bugs."""
        steps = 0
        while self.pending:
            self.step()
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(
                    f"engine did not drain within {max_steps} steps "
                    f"(queue={self.scheduler.queue_depth}, "
                    f"live={self.live_slots})")

    # -- internals -------------------------------------------------------
    def _decode_once(self):
        if _faults._armed:
            # chaos hook: a fault here is exactly a raising pool decode
            # (speculative mode included — the draft scan, the chunk
            # verify, and the rejection sample all sit behind this one
            # dispatch) — step() fails the engine typed and the
            # supervisor rebuilds; disarmed this is one module-flag
            # read per step
            _faults.check("serve.decode_step")
        live = np.asarray([s is not None for s in self._slots])
        n_live = int(live.sum())
        # watchdog heartbeat around the pool step (two clock calls,
        # only while monitoring is on); includes the np.asarray sync,
        # so the fed step time is real device time
        _mon = _monitor.active()
        _hb_t0 = time.perf_counter() if _mon else 0.0
        a_draft = None
        lps = None
        arena = self.paged_arena
        # (speculative paged steps run at full width: the DRAFT arena
        # is slot-indexed — compacting would have to gather/scatter
        # draft cache rows per step, which is exactly the copy tax
        # the block tables exist to avoid on the target side)
        if self.draft is not None:
            with _trace.span("serve/spec_step", cat="serve",
                             step=self.step_count, live=n_live,
                             paged=arena is not None):
                if arena is not None:
                    (out, a_draft, arena.pool_k, arena.pool_v,
                     self._dkc, self._dvc,
                     self._keys) = self._x.paged_spec_step(
                        self._params, self._d_params, arena.pool_k,
                        arena.pool_v, self._dkc, self._dvc,
                        self._block_tables(), jnp.asarray(self._toks),
                        jnp.asarray(self._pos), jnp.asarray(live),
                        self._keys, jnp.asarray(self._temps),
                        self._top_p, arena.block_size,
                        kernel=arena.config.kernel)
                else:
                    (out, a_draft, self._kc, self._vc, self._dkc,
                     self._dvc, self._keys) = self._x.pool_spec_step(
                        self._params, self._d_params, self._kc,
                        self._vc, self._dkc, self._dvc,
                        jnp.asarray(self._toks),
                        jnp.asarray(self._pos), jnp.asarray(live),
                        self._keys, jnp.asarray(self._temps),
                        self._top_p)
                if _stepprof._active:
                    _stepprof.push("sync")
                out = np.asarray(out)
                a_draft = np.asarray(a_draft)
                if _stepprof._active:
                    _stepprof.pop()
        else:
            # fork/structured pre-dispatch pass (paged, non-spec):
            # per-slot grammar masks computed on the HOST between
            # steps, stacked into one fixed-shape (S, V) bool input
            # (plain slots get all-True rows — a bitwise no-op in the
            # shared _sample), and the chosen-token logprob output
            # turned on whenever any live slot belongs to a fork
            # family.  Both are signature STATICS only in their
            # presence (masks-or-not, lp-or-not), so the warmed jit
            # cache covers every grammar and every fork pattern.
            masks_np = None
            need_lp = False
            if arena is not None:
                t_rej = None
                for i, s in enumerate(self._slots):
                    if s is None:
                        continue
                    if s.group is not None:
                        need_lp = True
                    if s.automaton is None:
                        continue
                    m = np.asarray(s.automaton.mask(s.astate), bool)
                    if not m.any():
                        # no vocab token continues the grammar from
                        # here (incomplete output, nothing legal to
                        # emit): that request is dead, typed — the
                        # engine keeps serving everyone else
                        t_rej = self._clock()
                        rid = s.handle.request.request_id
                        self._log.warning(
                            "structured automaton for %s reached a "
                            "dead end (no legal token); rejecting "
                            "that request", rid)
                        self._reject_live(
                            i, s,
                            ValueError(
                                f"{rid}: structured automaton state "
                                f"{s.astate!r} admits no vocab token "
                                f"— the grammar cannot complete from "
                                f"here"),
                            "structured_dead_end", t_rej)
                        continue
                    if masks_np is None:
                        masks_np = np.ones(
                            (self.max_slots, self.cfg.vocab_size),
                            bool)
                    masks_np[i] = m
                if t_rej is not None:
                    live = np.asarray(
                        [s is not None for s in self._slots])
                    n_live = int(live.sum())
                    if n_live == 0:
                        return
            with _trace.span("serve/decode_step", cat="serve",
                             step=self.step_count, live=n_live,
                             paged=arena is not None):
                if arena is not None:
                    # COMPACTED dispatch (the gather-tax round): run
                    # the pool step at the smallest width bucket
                    # covering the live slots instead of always at
                    # max_slots.  Legal precisely because the pool is
                    # paged — block tables address the KV, so a lane
                    # permutation is pure host bookkeeping (per-slot
                    # math is lane-independent; pad lanes are dead:
                    # clamped inputs, trash-table writes, keys never
                    # written back).  An over-provisioned engine
                    # (many slots, few live) stops paying dead-lane
                    # MLP/vocab/sampling work per step.
                    lanes = np.flatnonzero(live)
                    width = self._paged_width(len(lanes))
                    if width < self.max_slots:
                        sel = np.full(width, -1, np.intp)
                        sel[:len(lanes)] = lanes
                        live_w = np.zeros(width, bool)
                        live_w[:len(lanes)] = True
                        sel_in = np.where(sel < 0, 0, sel)
                        keys_w = _take_rows(self._keys,
                                            jnp.asarray(sel_in))
                        # masks/with_lp only when active: the sharded
                        # executors (tp/ep/pp) predate the fork
                        # signature and validation refuses fork on
                        # them, so the plain call must stay kwarg-free
                        fkw = {}
                        if masks_np is not None:
                            fkw["masks"] = jnp.asarray(masks_np[sel_in])
                        if need_lp:
                            fkw["with_lp"] = True
                        res = self._x.paged_decode_step(
                            self._params, arena.pool_k, arena.pool_v,
                            self._block_tables(list(sel)),
                            jnp.asarray(self._toks[sel_in]),
                            jnp.asarray(self._pos[sel_in]),
                            jnp.asarray(live_w), keys_w,
                            jnp.asarray(self._temps[sel_in]),
                            self._top_p, arena.block_size,
                            kernel=arena.config.kernel, **fkw)
                        nt_w, arena.pool_k, arena.pool_v, keys2 = \
                            res[:4]
                        self._keys = _set_rows(
                            self._keys, jnp.asarray(lanes),
                            keys2[:len(lanes)])
                        next_toks = np.zeros(self.max_slots, np.int32)
                        next_toks[lanes] = \
                            np.asarray(nt_w)[:len(lanes)]
                        if need_lp:
                            lps = np.zeros(self.max_slots)
                            lps[lanes] = \
                                np.asarray(res[4])[:len(lanes)]
                    else:
                        fkw = {}
                        if masks_np is not None:
                            fkw["masks"] = jnp.asarray(masks_np)
                        if need_lp:
                            fkw["with_lp"] = True
                        res = self._x.paged_decode_step(
                            self._params, arena.pool_k, arena.pool_v,
                            self._block_tables(),
                            jnp.asarray(self._toks),
                            jnp.asarray(self._pos), jnp.asarray(live),
                            self._keys, jnp.asarray(self._temps),
                            self._top_p, arena.block_size,
                            kernel=arena.config.kernel, **fkw)
                        (next_toks, arena.pool_k, arena.pool_v,
                         self._keys) = res[:4]
                        if need_lp:
                            lps = np.asarray(res[4])
                else:
                    next_toks, self._kc, self._vc, self._keys = \
                        self._x.pool_decode_step(
                            self._params, self._kc, self._vc,
                            jnp.asarray(self._toks),
                            jnp.asarray(self._pos),
                            jnp.asarray(live), self._keys,
                            jnp.asarray(self._temps), self._top_p)
                if _stepprof._active:
                    _stepprof.push("sync")
                next_toks = np.asarray(next_toks)
                if _stepprof._active:
                    _stepprof.pop()
        if _mon:
            _monitor.heartbeat(
                self._hb_source,
                step_time=time.perf_counter() - _hb_t0,
                fresh_compile=self.stats.decode_steps == 0)
        self.stats.on_decode_step(n_live)
        t_emit = self._clock()
        led = _reqs._ledger if _reqs._active else None
        lbl = self.stats.engine_label
        _sp = _stepprof._active
        if _sp:
            _stepprof.push("emit")
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            rid = slot.handle.request.request_id
            if a_draft is None:
                if lps is not None and slot.group is not None:
                    # best-of-n ranking signal: cumulative chosen-
                    # token logprob under the raw distribution,
                    # accumulated BEFORE _emit (which may retire the
                    # slot and seal the score into the result)
                    slot.score += float(lps[i])
                self._emit(i, slot, int(next_toks[i]), t_emit)
                if led is not None:
                    if _sp:
                        _stepprof.push("ledger")
                    led.on_step(rid, engine=lbl, t=t_emit, tokens=1)
                    if _sp:
                        _stepprof.pop()
                self._toks[i] = next_toks[i]
                self._pos[i] += 1
                continue
            # speculative: up to a_draft[i] + 1 accepted tokens this
            # step.  Emission stops mid-chunk the moment the request
            # retires (budget hit, stop token) or rejects (raising
            # on_token) — tokens past that point are discarded, and
            # their cache rows are dead weight the next admission's
            # full-row write replaces
            a = int(a_draft[i]) + 1
            self.stats.on_spec(int(a_draft[i]), self.spec_k - 1)
            emitted = 0
            for j in range(a):
                self._emit(i, slot, int(out[i, j]), t_emit)
                emitted += 1
                if self._slots[i] is not slot:
                    break
            if led is not None:
                # per-step ledger record with the chunk's acceptance:
                # emitted tokens (may stop mid-chunk), accepted
                # proposals, proposals offered (lands on the sealed
                # entry when the last token retired the request)
                if _sp:
                    _stepprof.push("ledger")
                led.on_step(rid, engine=lbl, t=t_emit, tokens=emitted,
                            accepted=int(a_draft[i]),
                            drafted=self.spec_k - 1)
                if _sp:
                    _stepprof.pop()
            if self._slots[i] is slot:
                self._toks[i] = int(out[i, emitted - 1])
                self._pos[i] += emitted
        if _sp:
            _stepprof.pop()

    def _emit(self, idx, slot, token, now):
        slot.emitted.append(token)
        slot.remaining -= 1
        req = slot.handle.request
        self.stats.on_token()
        if slot.first_token_time is None:
            slot.first_token_time = now
        if req.on_token is not None:
            try:
                req.on_token(req, token)
            except Exception as e:
                # a raising CLIENT callback is that request's failure,
                # not an engine death: reject it typed-as-raised, free
                # the slot, and keep serving the other tenants (a
                # blanket engine _fail here would let one bad streaming
                # client burn everyone — and the supervisor's restart
                # budget with it)
                self._log.warning(
                    "on_token callback for %s raised (%r); rejecting "
                    "that request, slot %d freed", req.request_id, e,
                    idx)
                self._reject_live(idx, slot, e, "on_token_callback",
                                  now)
                return
        if slot.automaton is not None:
            # structured decoding: advance the grammar with the token
            # the mask admitted.  A mismatch here means the mask and
            # the automaton disagree — an automaton bug, charged to
            # THIS request (typed reject), never an engine death.
            try:
                slot.astate = slot.automaton.advance(slot.astate,
                                                     token)
            except Exception as e:
                self._log.warning(
                    "structured automaton for %s rejected its own "
                    "masked token (%r); rejecting that request",
                    req.request_id, e)
                self._reject_live(idx, slot, e, "structured_advance",
                                  now)
                return
            if slot.automaton.done(slot.astate):
                self._retire(idx, slot, now, finish_reason="stop")
                return
        stop = (req.stop_token is not None and token == req.stop_token)
        if stop or slot.remaining <= 0:
            # budget/EOS retire is per TOKEN, not per step: a
            # multi-token speculative chunk retires mid-chunk the
            # moment the budget or the stop token lands, and the
            # chunk's remaining tokens are never emitted
            self._retire(idx, slot, now,
                         finish_reason="stop" if stop else "length")

    def _retire(self, idx, slot, now, finish_reason="length"):
        req = slot.handle.request
        n = len(slot.emitted)
        _sp = _stepprof._active
        if _sp:
            _stepprof.push("retire")
        _trace.event("serve/retire", cat="serve",
                     request=req.request_id, slot=idx, tokens=n,
                     step=self.step_count)
        if _reqs._active:
            if _sp:
                _stepprof.push("ledger")
            _reqs._ledger.on_retire(req.request_id,
                                    engine=self.stats.engine_label,
                                    t=now, finish_reason=finish_reason,
                                    tokens=n)
            if _sp:
                _stepprof.pop()
        submit_t = getattr(slot.handle, "_submit_time", slot.admit_time)
        ttft = slot.first_token_time - submit_t
        tpot = ((now - slot.first_token_time) / (n - 1)
                if n > 1 else None)
        result = GenerationResult(
            request_id=req.request_id,
            tokens=np.concatenate(
                [req.prompt_ids,
                 np.asarray(slot.emitted, np.int32)]),
            finish_reason=finish_reason,
            ttft=ttft, tpot=tpot,
            queue_time=slot.admit_time - submit_t,
            admitted_step=slot.admitted_step,
            finished_step=self.step_count,
            branch=slot.branch,
            score=(slot.score if slot.group is not None else None))
        if self.paged_arena is not None:
            self._paged_retire(idx, slot, req, result)
        elif self.prefix_cache is not None:
            self._prefix_retire(idx, slot, req, result)
        elif req.pin_session:
            # no cache: the session handle still works, continuation
            # just runs through cold prefill
            result.session = SessionHandle(result.tokens)
        slot.handle._finish(result)
        self.stats.on_complete(result)
        self._slots[idx] = None
        # the caller's handle owns the result now; dropping the routing
        # entry keeps a long-lived engine's memory flat under sustained
        # traffic
        self._handles.pop(req.request_id, None)
        if self.paged_arena is not None:
            self._fork_gauge()
        if _sp:
            _stepprof.pop()

    def _reject_live(self, idx, slot, error, reason, now):
        """Reject a LIVE slot's request typed (client callback raised,
        structured dead end, CoW copy faulted): release its prefix
        refs, free/deref its blocks, drop the slot, and seal the
        handle with ``error``.  Started=True — tokens streamed, never
        requeue-safe.  The engine keeps serving everyone else."""
        req = slot.handle.request
        self._release_prefix(slot)
        self._free_slot_blocks(slot)
        self._slots[idx] = None
        self._handles.pop(req.request_id, None)
        _trace.event("serve/request_rejected", cat="serve",
                     request=req.request_id, reason=reason)
        if _reqs._active:
            _reqs._ledger.on_reject(
                req.request_id, t=now, reason=reason,
                engine=self.stats.engine_label, started=True)
        slot.handle._reject(error)
        if self.paged_arena is not None:
            self._fork_gauge()

    def _fork_gauge(self):
        if self._g_fork_shared is not None:
            self._g_fork_shared.set(self.paged_arena.shared_blocks)

    def _release_prefix(self, slot):
        if self.prefix_cache is not None and slot.prefix_nodes:
            self.prefix_cache.release(slot.prefix_nodes)
            slot.prefix_nodes = []

    # -- paged-arena internals -------------------------------------------
    def _free_slot_blocks(self, slot):
        """Teardown for a paged slot that will not retire normally:
        free its private blocks (shared prefix blocks are only
        ref-released, by ``_release_prefix``).  Deferred admission
        writes flush FIRST: a block freed here could be re-allocated
        by a later same-pass admission, and a pending scatter landing
        after that would clobber the new owner."""
        if self._pending_scatter or self._pending_keys:
            self._flush_admission_writes()
        if self.paged_arena is not None and slot.blocks:
            # windowed slots hold trash sentinels at already-dropped
            # leading lanes — those were freed when they left the
            # window, so only real ids return to the free list
            self.paged_arena.free(
                [b for b in slot.blocks[slot.n_shared:]
                 if b != self.paged_arena.trash])
            slot.blocks = []

    def _block_tables(self, idxs=None):
        """The (S, W//B) int32 block-table input of the paged pool
        steps: each live slot's block list, trash-padded (dead slots
        are all-trash, so their writes land in the trash block).
        ``idxs``: optional slot-id row order for a COMPACTED step
        (entries < 0 are pad lanes — all-trash rows)."""
        arena = self.paged_arena
        rows = (range(self.max_slots) if idxs is None else idxs)
        tables = np.full((len(rows), arena.row_blocks),
                         arena.trash, np.int32)
        for r, i in enumerate(rows):
            slot = self._slots[i] if i >= 0 else None
            if slot is not None:
                tables[r, :len(slot.blocks)] = slot.blocks
        return jnp.asarray(tables)

    def _paged_width(self, n_live):
        """Decode-dispatch width for ``n_live`` live slots: the
        smallest HALVING bucket of ``max_slots`` still covering them
        ({S, S/2, S/4, ...} — one compiled signature per bucket,
        ~log2(S) of them, all covered by a warmup pass over the same
        workload, since the live trajectory is deterministic).
        The paged pool makes this free: KV is addressed by BLOCK
        TABLES, not by slot index, so a step over any subset of slots
        is just a shorter table/token batch — no cache rows move.
        The slot arena cannot compact (its KV is indexed by slot),
        which is why over-provisioned paged engines stop paying the
        dead-lane tax the moment occupancy sits below the peak — the
        per-step decode cost is COMPUTE-bound in the lane count
        (MLP + vocab per lane), so width tracks occupancy nearly 1:1
        in step time.  Halving (not a finer ladder) is deliberate:
        each sub-width step pays two small key-compaction dispatches,
        so buckets must buy a real width drop to be worth switching
        (measured: a 3/4 ladder was net SLOWER at the bench
        geometry)."""
        w = self.max_slots
        while w >= 2 and w >= 2 * n_live:
            w //= 2
        return max(w, n_live)

    def _grow_live_slots(self):
        """Block-by-block growth: before the pool step dispatches,
        every live slot must own the block(s) covering the position(s)
        this step writes (``pos`` .. ``pos + spec_k - 1`` on a
        speculative engine).  A slot that cannot grow — pool exhausted
        and no strictly-lower-priority victim to preempt — swaps
        ITSELF out: its blocks free the pool for the others and it
        resumes (byte-identical) once capacity returns, so the pool
        never livelocks with every slot too big to advance."""
        arena = self.paged_arena
        B = arena.block_size
        W = self._window
        for i in range(self.max_slots):
            slot = self._slots[i]
            if slot is None:
                continue
            pos = int(self._pos[i])
            if W is not None:
                # DROP out-of-window blocks first (so this slot's own
                # freed block can satisfy its growth below): block j
                # is fully dead once its last position (j+1)*B - 1
                # falls below the lowest key the next query attends
                # (pos - W + 1) — the long-chat O(window) memory
                # model.  The table lane keeps a trash sentinel so
                # block indices stay positional.
                dead = max(0, (pos - W + 1) // B)
                drop = [b for b in slot.blocks[:dead]
                        if b != arena.trash]
                if drop:
                    if self._pending_scatter or self._pending_keys:
                        # a deferred admission write could target a
                        # block about to be freed-and-reallocated
                        self._flush_admission_writes()
                    arena.free(drop)
                    arena.on_window_drop(len(drop))
                    for j in range(min(dead, len(slot.blocks))):
                        slot.blocks[j] = arena.trash
            if slot.cow:
                # copy-on-first-write (serve/fork.py): this step
                # writes position pos into block pos // B — if a
                # sibling still references that block, give this slot
                # a private byte copy BEFORE the dispatch so the
                # sibling's KV is never clobbered.  Fork geometry
                # keeps wb >= n_shared always (branches share at the
                # write frontier, past the cache-owned prefix), so
                # cache-owned blocks are never copied here.
                wb = pos // B
                if wb < len(slot.blocks) \
                        and arena.is_shared(slot.blocks[wb]):
                    if not self._cow_copy(i, slot, wb):
                        continue
            need = (pos + self._spec_pad) // B + 1
            short = need - len(slot.blocks)
            if short <= 0:
                continue
            prio = getattr(slot.handle.request, "priority", 0)
            got = self._alloc_blocks(short, prio, exclude_idx=i)
            if got is None:
                self._preempt_slot(i, reason="pool_exhausted")
                continue
            slot.blocks.extend(got)

    def _cow_copy(self, idx, slot, wb):
        """Give ``slot`` a private copy of its sibling-shared block
        ``wb`` before this step writes into it.  Returns False when
        the slot did not survive (pool exhausted → self-preempt, or
        the copy dispatch faulted → typed reject) — the caller skips
        the slot this pass."""
        arena = self.paged_arena
        prio = getattr(slot.handle.request, "priority", 0)
        got = self._alloc_blocks(1, prio, exclude_idx=idx)
        if got is None:
            self._preempt_slot(idx, reason="pool_exhausted")
            return False
        old = slot.blocks[wb]
        try:
            arena.copy_block(old, got[0])
        except Exception as e:
            # the CoW copy is this BRANCH's work, not the engine's:
            # a fault here (resilience site serve.fork_copy) rejects
            # the one branch typed and frees its claim — siblings and
            # unrelated tenants keep streaming
            arena.free(got)
            self._log.warning(
                "CoW block copy for %s faulted (%r); rejecting that "
                "branch, slot %d freed",
                slot.handle.request.request_id, e, idx)
            self._reject_live(idx, slot, e, "fork_copy", self._clock())
            return False
        slot.blocks[wb] = got[0]
        arena.free([old])  # drop this slot's reference; sibling keeps it
        self._c_fork_cow.inc()
        self._fork_gauge()
        return True

    def _alloc_blocks(self, n, priority, exclude_idx=None):
        """``n`` pool blocks for a request at ``priority``, evicting
        unreferenced cached blocks first (arena.alloc) and then
        PREEMPTING strictly-lower-priority live slots (lowest
        priority, then latest admitted) until the allocation fits or
        no victim remains.  Strictly-lower only: equal-priority slots
        never preempt each other, which is what makes every preemption
        chain terminate.

        Feasibility is checked BEFORE any side effect: when free +
        evictable + every eligible victim's private blocks still
        cannot cover ``n`` (e.g. pinned sessions hold unevictable
        references), the claimant simply waits — preempting victims
        that cannot make the allocation fit would be pure swap churn,
        and with a permanently infeasible head request it would
        livelock the engine (preempt → fail → resume → preempt)."""
        arena = self.paged_arena
        avail = arena.blocks_free
        if self.prefix_cache is not None:
            avail += self.prefix_cache.evictable_blocks()
        trash = arena.trash
        # a victim's sibling-shared blocks do NOT come back to the
        # free list (free only drops a reference), so they cannot
        # count toward feasibility
        avail += sum(
            sum(1 for b in s.blocks[s.n_shared:]
                if b != trash and not arena.is_shared(b))
            for i, s in enumerate(self._slots)
            if s is not None and i != exclude_idx
            and getattr(s.handle.request, "priority", 0) < priority)
        if n > avail:
            return None
        while True:
            got = arena.alloc(n)
            if got is not None:
                return got
            victim = self._pick_victim(priority, exclude=exclude_idx)
            if victim is None:
                return None
            self._preempt_slot(victim, reason="preempted")

    def _pick_victim(self, below_priority, exclude=None):
        """The live slot to preempt for a ``below_priority`` claimant:
        strictly lower priority only; lowest priority first, ties to
        the latest-admitted (least sunk progress).  None when nothing
        qualifies."""
        best = None
        for i, s in enumerate(self._slots):
            if s is None or i == exclude:
                continue
            p = getattr(s.handle.request, "priority", 0)
            if p >= below_priority:
                continue
            k = (p, -s.admitted_step)
            if best is None or k < best[0]:
                best = (k, i)
        return None if best is None else best[1]

    def _preempt_slot(self, idx, reason):
        """Swap one live request's state to HOST memory and free its
        blocks: one fixed-shape gather + device sync for the target
        lanes (plus the draft row on a speculative engine), every
        scrap of host bookkeeping saved, shared prefix refs released.
        The byte copy is what keeps a resumed request's remaining
        token stream identical to the uninterrupted run's — see
        serve/paged.py's module docstring for why recompute-on-resume
        could not promise that."""
        arena = self.paged_arena
        # a same-pass admission's deferred writes must land before
        # this gather reads the pool (and before self._keys[idx] is
        # snapshotted below) — the victim could be a slot admitted
        # earlier in the very pass that is now preempting
        if self._pending_scatter or self._pending_keys:
            self._flush_admission_writes()
        slot = self._slots[idx]
        req = slot.handle.request
        rid = req.request_id
        pos = int(self._pos[idx])
        sw = _Swapped()
        sw.handle = slot.handle
        sw.request = req
        sw.emitted = slot.emitted
        sw.remaining = slot.remaining
        sw.first_token_time = slot.first_token_time
        sw.admit_time = slot.admit_time
        sw.admitted_step = slot.admitted_step
        sw.pos = pos
        sw.tok = int(self._toks[idx])
        sw.temp = float(self._temps[idx])
        sw.key = np.asarray(self._keys[idx])
        # windowed slots: leading lanes already dropped to trash hold
        # no bytes — swap only the live tail, and remember the lane
        # offset so resume rebuilds the same positional table (the
        # swap image stays O(window) like the device footprint)
        sw.j_lo = 0
        if self._window is not None:
            while sw.j_lo < len(slot.blocks) \
                    and slot.blocks[sw.j_lo] == arena.trash:
                sw.j_lo += 1
        sw.n_data = max(0, (pos - 1) // arena.block_size + 1
                        - sw.j_lo)
        sw.seq = next(self._swap_seq)
        sw.t_preempt = self._clock()
        # fork/structured state rides the swap image too: the resumed
        # slot scores and masks exactly as the uninterrupted one would
        sw.group = slot.group
        sw.branch = slot.branch
        sw.score = slot.score
        sw.automaton = slot.automaton
        sw.astate = slot.astate
        # the swap image rides the shared versioned host format
        # (serve/kvimage.py) — the same one KV shipping uses, so the
        # two host-image paths cannot drift
        sw.image = arena.swap_out(slot.blocks[sw.j_lo:], sw.n_data)
        sw.dkc_h = sw.dvc_h = None
        if self.draft is not None:
            dkc_row, dvc_row = _read_slot(self._dkc, self._dvc,
                                          jnp.int32(idx))
            sw.dkc_h = jax.tree.map(np.asarray, dkc_row)
            sw.dvc_h = jax.tree.map(np.asarray, dvc_row)
        n_freed = sum(1 for b in slot.blocks[slot.n_shared:]
                      if b != arena.trash)
        self._free_slot_blocks(slot)
        self._release_prefix(slot)
        self._slots[idx] = None
        self._swapped.append(sw)
        arena.on_preempt()
        _trace.event("serve/preempt", cat="serve", request=rid,
                     slot=idx, reason=reason, pos=pos,
                     blocks_freed=n_freed, tokens=len(sw.emitted))
        if _reqs._active:
            _reqs._ledger.on_preempt(rid,
                                     engine=self.stats.engine_label,
                                     t=sw.t_preempt)
        self._log.info("preempted %s (%s): %d blocks freed at pos %d",
                       rid, reason, n_freed, pos)

    def _try_resume(self, now):
        """Resume swapped-out requests, highest priority first (FIFO
        within a class): allocate the full block need (preempting
        strictly-lower live slots if necessary), scatter the host copy
        back, restore the slot state and sampling key.  Head-of-line
        semantics: if the best swapped request does not fit, nothing
        behind it jumps the line."""
        if not self._swapped:
            return
        arena = self.paged_arena
        B = arena.block_size
        while self._swapped:
            # re-sort every iteration: a resume's own preemption (of a
            # strictly-lower live slot) APPENDS to the swap list, and
            # the next head must still be the highest-priority oldest
            self._swapped.sort(key=lambda s: (-s.priority, s.seq))
            # a slot reserved by an in-flight chunked prefill is NOT
            # free: a resume landing there would be clobbered when
            # _finish_prefilling promotes the reservation
            free = self._free_slots()
            if not free:
                return
            sw = self._swapped[0]
            j_lo = getattr(sw, "j_lo", 0)
            need = (sw.pos + self._spec_pad) // B + 1 - j_lo
            blocks = self._alloc_blocks(need, sw.priority)
            if blocks is None:
                return
            idx = free[0]
            arena.swap_in(sw.image, blocks[:sw.n_data])
            if self.draft is not None and sw.dkc_h is not None:
                self._dkc, self._dvc = _write_slot(
                    self._dkc, self._dvc,
                    jax.tree.map(jnp.asarray, sw.dkc_h),
                    jax.tree.map(jnp.asarray, sw.dvc_h),
                    jnp.int32(idx))
            slot = _Slot(sw.handle, sw.remaining, sw.admit_time,
                         sw.admitted_step)
            slot.emitted = sw.emitted
            slot.first_token_time = sw.first_token_time
            # windowed: rebuild the positional table with the dropped
            # leading lanes as trash sentinels (same shape the
            # uninterrupted slot would hold at this pos)
            slot.blocks = [arena.trash] * j_lo + blocks
            slot.n_shared = 0
            # the swap-in scattered a private byte copy of every
            # block, so the resumed slot shares nothing: cow stays
            # False, but its fork identity/score and grammar state
            # continue where they left off
            slot.group = getattr(sw, "group", None)
            slot.branch = getattr(sw, "branch", 0)
            slot.score = getattr(sw, "score", 0.0)
            slot.automaton = getattr(sw, "automaton", None)
            slot.astate = getattr(sw, "astate", None)
            self._slots[idx] = slot
            self._toks[idx] = sw.tok
            self._pos[idx] = sw.pos
            self._temps[idx] = sw.temp
            self._keys = self._keys.at[idx].set(jnp.asarray(sw.key))
            self._swapped.pop(0)
            rid = sw.request.request_id
            _trace.event("serve/resume", cat="serve", request=rid,
                         slot=idx, pos=sw.pos,
                         swapped_s=now - sw.t_preempt)
            if _reqs._active:
                _reqs._ledger.on_resume(
                    rid, engine=self.stats.engine_label, t=now)
            self._log.info("resumed %s after %.3fs swapped", rid,
                           now - sw.t_preempt)

    def _paged_retire(self, idx, slot, req, result):
        """Retire teardown for the paged arena.  Donation is
        ZERO-COPY: the slot's prompt blocks already live in the shared
        pool, so the radix tree ADOPTS them (``adopt_blocks``) instead
        of scattering a copy — only a pinned session's generated
        windows pay a re-canonicalization chunk pass (decode-step KV
        is not canonical; same analysis as ``_prefix_retire``)."""
        arena = self.paged_arena
        cache = self.prefix_cache
        B = arena.block_size
        try:
            if cache is None:
                if req.pin_session:
                    result.session = SessionHandle(result.tokens)
                return
            plen = len(req.prompt_ids)
            total = len(result.tokens)
            want_session = bool(req.pin_session)
            n_goal = (total // B) if want_session else (plen // B)
            # the FINAL emitted token's KV position is never written
            # (nothing decodes after it), so at block_size=1 its block
            # was never allocated — a session pins one block less (the
            # next turn's admission recomputes the tail block anyway)
            n_goal = min(n_goal, len(slot.blocks))
            # fork: never adopt a block a LIVE sibling still shares —
            # the tree would own a block the sibling may CoW-free, and
            # double-ownership breaks the accounting invariant.  The
            # LAST retiring sibling sees refcount 1 everywhere and
            # adopts the full prefix, so the cache still wins it.
            for j in range(slot.n_shared, n_goal):
                if arena.is_shared(slot.blocks[j]):
                    n_goal = j
                    break
            path = []
            if n_goal > 0:
                if want_session and n_goal > plen // B:
                    kc_row, vc_row = arena.gather_row(slot.blocks)
                    ids = np.zeros((1, self.max_len), np.int32)
                    ids[0, :total] = result.tokens
                    ids_j = jnp.asarray(ids)
                    for j in range(plen // B, n_goal):
                        _, kc_row, vc_row = self._x.chunk_row(
                            self._params, ids_j, kc_row, vc_row,
                            jnp.int32(j * B))
                    arena.scatter_row(
                        kc_row, vc_row,
                        {j: slot.blocks[j]
                         for j in range(plen // B, n_goal)})
                path = cache.adopt_blocks(result.tokens, slot.blocks,
                                          n_goal)
            if want_session:
                cache.acquire(path)
                result.session = SessionHandle(result.tokens, cache,
                                               path)
            # free the private blocks the tree did not adopt (the
            # decode-region blocks, the growth block, and any lane a
            # sibling's earlier donation made a duplicate of)
            adopted = {n.block for n in path}
            arena.free([b for b in slot.blocks[slot.n_shared:]
                        if b not in adopted])
            slot.blocks = []
        finally:
            self._release_prefix(slot)
            # exception path: nothing was adopted, every private
            # block is still slot-owned — free them so a raising
            # donation cannot leak pool capacity
            self._free_slot_blocks(slot)

    def _prefix_retire(self, idx, slot, req, result):
        """Donate the retired request's prefix back to the radix tree
        (its prompt's full blocks are canonical prefill K/V sitting in
        the slot row — decode never touched positions < prompt_len),
        and pin the FULL sequence for ``pin_session`` requests.

        Session pinning re-canonicalizes the generated region first:
        decode-step K/V is not bitwise prefill K/V (~1e-6 drift), so
        the windows containing generated tokens are recomputed through
        the same ``_chunk_row`` executable warm admission uses — one
        chunk pass at retire (off the TTFT path) keeps every future
        warm turn byte-identical to cold prefill."""
        cache = self.prefix_cache
        B = cache.block_size
        try:
            plen = len(req.prompt_ids)
            total = len(result.tokens)
            want_session = bool(req.pin_session)
            n_goal = (total // B) if want_session else (plen // B)
            path = []
            if n_goal > 0:
                existing = cache.lookup(result.tokens)[:n_goal]
                if len(existing) == n_goal:
                    # everything already cached (steady-state hit
                    # regime): no row gather, no chunks, no scatter —
                    # just refresh recency
                    cache.touch(existing)
                    path = existing
                else:
                    kc_row, vc_row = self._x.read_slot(
                        self._kc, self._vc, jnp.int32(idx))
                    if want_session and total // B > plen // B:
                        ids = np.zeros((1, self.max_len), np.int32)
                        ids[0, :total] = result.tokens
                        ids_j = jnp.asarray(ids)
                        for j in range(plen // B, total // B):
                            _, kc_row, vc_row = self._x.chunk_row(
                                self._params, ids_j, kc_row, vc_row,
                                jnp.int32(j * B))
                    path = cache.donate_from_row(result.tokens, kc_row,
                                                 vc_row, n_goal)
            if want_session:
                cache.acquire(path)
                result.session = SessionHandle(result.tokens, cache,
                                               path)
        finally:
            self._release_prefix(slot)

    def _schedule(self, now):
        if self.paged_arena is not None:
            # swapped requests re-enter BEFORE new admissions: they
            # already made progress (and streamed tokens), so leaving
            # them swapped behind fresh arrivals would invert both the
            # priority order and the latency story
            self._try_resume(now)
        if self._budget is not None:
            # chunked-prefill token budget (the long-context round):
            # a dedicated pass that first advances in-flight chunked
            # prefills and then admits new work against the step's
            # remaining token budget — one admission can span many
            # steps, so the whole-prompt flow below does not apply
            self._schedule_budgeted(now)
            return
        free = self._free_slots()
        if not free and self.scheduler.queue_depth == 0:
            return
        admit = self._sched_admissions(len(free), now)
        blocked_p = self._blocked_priority()
        # BATCHED pass prefill (the gather-tax round): a multi-request
        # pass on a cold paged engine (no prefix cache to consult, no
        # draft rows to build) prefills every admission in ONE
        # dispatch + one host sync up front, so an arrival burst costs
        # the live decode lanes one prefill's latency instead of K —
        # the computation is pure (block allocation happens per
        # request below), so a request that ultimately requeues only
        # wasted its row, never pool state
        # only the prefix that will actually be admitted is worth
        # prefilling: admission order blocks at the first request a
        # swapped higher-priority request outranks, so batching past
        # it would pay a whole discarded dispatch + sync EVERY pass
        # for as long as the blockage lasts
        batchable = admit
        if blocked_p is not None:
            batchable = []
            for r in admit:
                if getattr(r, "priority", 0) <= blocked_p:
                    break
                batchable.append(r)
        # forked (n>1) and structured admissions keep the per-request
        # path: the batch prefill samples tok0 unmasked and its rows
        # predate the fork bookkeeping
        if not all(getattr(r, "n", 1) == 1
                   and getattr(r, "structured", None) is None
                   for r in batchable):
            batchable = []
        prefilled = {}
        if (self.paged_arena is not None and self.draft is None
                and self.prefix_cache is None and not self._ring
                and len(batchable) > 1
                # int32 seed lanes: an exotic >= 2^31 seed keeps the
                # per-request path (identical streams either way — the
                # batch must never silently rekey a request)
                and all(0 <= int(r.seed) < 2 ** 31 for r in batchable)):
            # prefilled rows are PURE functions of (prompt, seed,
            # temp): when a capacity-blocked pass requeues the same
            # requests, reuse the batch instead of re-dispatching it
            # every step for as long as the blockage lasts.  Keyed on
            # request OBJECT identity (the cache holds the refs, so
            # an id cannot be recycled under it); any change in the
            # pass's membership recomputes
            cached = self._batch_cache
            if (cached is not None
                    and len(cached[0]) == len(batchable)
                    and all(a is b
                            for a, b in zip(cached[0], batchable))):
                prefilled, self._admit_batch = cached[1], cached[2]
            else:
                prefilled = self._prefill_admissions(batchable)
                self._batch_cache = (tuple(batchable), prefilled,
                                     self._admit_batch)
        for k, req in enumerate(admit):
            n_br = getattr(req, "n", 1)
            ok = False
            if (blocked_p is None
                    or getattr(req, "priority", 0) > blocked_p) \
                    and len(free) >= n_br:
                # n>1 admits only when the WHOLE family fits this
                # pass (one slot per branch): a partially-forked
                # family would leave branch count dependent on
                # scheduling noise
                ph = self._handles[req.request_id]
                ok = self._admit(free.pop(0), req, now,
                                 prefilled=prefilled.get(
                                     req.request_id))
                if ok and n_br > 1:
                    self._fork_group_admit(req, ph, free, now)
            if not ok:
                # capacity block: the head request's blocks do not fit
                # even after eviction + priority preemption (or a
                # swapped request outranks it).  Push it AND
                # everything scheduled behind it back to the queue
                # front in original order — admission order blocks,
                # it never skips
                for r in reversed(admit[k:]):
                    self.scheduler.requeue_front(r)
                break
        else:
            # every scheduled request admitted: the cached pass batch
            # can never recur, so release its device rows — without
            # this, one large burst's stacked prefill KV would stay
            # pinned for the engine's lifetime
            self._batch_cache = None
        if self._admit_batch is not None:
            self._flush_admission_writes(drop_batch=True)

    def _free_slots(self):
        """Slot indices genuinely available for admission or resume:
        unoccupied AND not reserved by an in-flight chunked prefill."""
        return [i for i, s in enumerate(self._slots)
                if s is None and i not in self._prefilling]

    # -- CoW KV forking (serve/fork.py) ----------------------------------
    def _fork_group_admit(self, req, handle, free, now):
        """Spawn branches 1..n-1 of an ``n > 1`` admission off the
        freshly admitted parent slot, inside the same scheduling pass
        (the admit loop reserved one free slot per branch up front).
        If tok0 already resolved the parent — its stop token landed on
        the first sample, or its on_token callback rejected it — every
        sibling would have produced the same single token, so they
        seal immediately with the parent's outcome instead of
        forking."""
        rid = req.request_id
        pidx = next((i for i, s in enumerate(self._slots)
                     if s is not None and s.handle is handle), None)
        if pidx is None:
            for k in range(1, req.n):
                ch = RequestHandle(req)
                if handle._result is not None:
                    ch._finish(replace(handle._result,
                                       request_id=f"{rid}#{k}",
                                       branch=k))
                else:
                    ch._reject(handle._error)
                handle._fork_children.append(ch)
            return
        parent = self._slots[pidx]
        if parent.group is None:
            parent.group = next(self._fork_seq)
        for k in range(1, req.n):
            self._spawn_branch(pidx, free.pop(0), k, now)

    def _spawn_branch(self, parent_idx, child_idx, branch, now,
                      seed=None, max_new=None):
        """Clone the live slot at ``parent_idx`` into ``child_idx`` as
        fork branch ``branch``: the child's block table is a COPY of
        the parent's with every non-cache-owned block's arena refcount
        bumped (zero KV bytes move), prefix-cache refs re-acquired,
        and host decode state (token, position, temperature, emitted
        list, grammar state) duplicated.  Both slots turn ``cow`` on:
        the next write into a still-shared block copies it first
        (:meth:`_cow_copy`).  The child re-keys via
        ``fold_in(parent_key, branch)`` (or a fresh chain from
        ``seed``) so siblings sample independently from the shared
        distribution."""
        arena = self.paged_arena
        cache = self.prefix_cache
        # deferred same-pass admission writes must land before the
        # parent's key/pool state is read below
        if self._pending_scatter or self._pending_keys:
            self._flush_admission_writes()
        parent = self._slots[parent_idx]
        preq = parent.handle.request
        rid = preq.request_id
        child_rid = f"{rid}#{branch}"
        child_req = replace(
            preq, request_id=child_rid, n=1,
            max_new_tokens=(preq.max_new_tokens if max_new is None
                            else int(max_new)))
        child_handle = RequestHandle(child_req)
        child_handle._submit_time = now
        child = _Slot(child_handle,
                      parent.remaining if max_new is None
                      else int(max_new),
                      now, self.step_count)
        child.emitted = list(parent.emitted)
        # the branch point IS its first token: a branch pruned before
        # its first own decode still seals with real latency numbers
        child.first_token_time = now
        shared = [b for b in parent.blocks[parent.n_shared:]
                  if b != arena.trash]
        arena.share(shared)
        child.blocks = list(parent.blocks)
        if cache is not None and parent.prefix_nodes:
            cache.acquire(parent.prefix_nodes)
            child.prefix_nodes = list(parent.prefix_nodes)
        child.n_shared = parent.n_shared
        child.group = parent.group
        child.branch = branch
        child.score = parent.score
        child.automaton = parent.automaton
        child.astate = parent.astate
        parent.cow = child.cow = True
        if seed is None:
            ck = jax.random.fold_in(self._keys[parent_idx],
                                    int(branch))
        else:
            ck = jax.random.split(
                jax.random.PRNGKey(int(seed)), 1)[0]
        self._keys = self._keys.at[child_idx].set(ck)
        self._toks[child_idx] = self._toks[parent_idx]
        self._pos[child_idx] = self._pos[parent_idx]
        self._temps[child_idx] = self._temps[parent_idx]
        self._slots[child_idx] = child
        self._handles[child_rid] = child_handle
        kids = getattr(parent.handle, "_fork_children", None)
        if kids is None:
            kids = parent.handle._fork_children = []
        kids.append(child_handle)
        # a branch is a submission that skipped the queue and the
        # prefill (its KV is the parent's, by reference): submitted
        # counts balance completions, but no admission latency sample
        # is recorded — zero queue/prefill would drag the TTFT
        # distribution with samples no client experienced
        self.stats.on_submit()
        if _reqs._active:
            lbl = self.stats.engine_label
            _reqs._ledger.on_submit(
                child_rid, engine=lbl, t=now,
                prompt_len=len(preq.prompt_ids),
                max_new_tokens=child_req.max_new_tokens)
            _reqs._ledger.on_admit(child_rid, engine=lbl, t=now,
                                   slot=child_idx,
                                   step=self.step_count,
                                   branch=branch)
            _reqs._ledger.on_first_token(child_rid, engine=lbl, t=now)
        _trace.event("serve/fork", cat="serve", request=child_rid,
                     parent=rid, slot=child_idx, branch=branch,
                     shared_blocks=len(shared),
                     pos=int(self._pos[parent_idx]))
        self._c_fork_branches.inc()
        self._fork_gauge()
        return child_handle

    def fork(self, request_id, *, seed=None, max_new_tokens=None):
        """Split the LIVE request ``request_id`` into two branches
        sharing every block decoded so far copy-on-write (tree-shaped
        search: fork the promising branch, ``prune`` the losers).
        Returns a :class:`~singa_tpu.serve.fork.BranchHandle` for the
        new branch; the original keeps streaming unchanged.  ``seed``
        re-keys the new branch from a fresh chain (default:
        ``fold_in`` of the parent's current key by the branch index);
        ``max_new_tokens`` caps the new branch's REMAINING budget
        (default: inherit the parent's)."""
        if self._closed:
            raise RuntimeError(
                "engine is closed; build a new one with model.serve()")
        if self._failed:
            raise EngineFailedError(
                "engine has failed; rebuild it (EngineSupervisor does "
                "this automatically)", engine_step=self.step_count)
        if (self.paged_arena is None or self.draft is not None
                or self._shard is not None or self._window is not None
                or self._ring):
            raise ValueError(
                "fork() needs a plain paged engine (no draft, no "
                "tensor-parallel backend, no sliding window, no ring "
                "prefill) — same support matrix as "
                "GenerationRequest(n>1)")
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        pidx = next(
            (i for i, s in enumerate(self._slots)
             if s is not None
             and s.handle.request.request_id == request_id), None)
        if pidx is None:
            if self._handles.get(request_id) is None:
                raise ValueError(
                    f"{request_id}: unknown or already finished — "
                    f"fork() splits a LIVE branch")
            if any(sw.request.request_id == request_id
                   for sw in self._swapped):
                state = "swapped out (preempted)"
            elif any(pf.request.request_id == request_id
                     for pf in self._prefilling.values()):
                state = "mid chunked prefill"
            else:
                state = "still queued"
            raise ValueError(
                f"{request_id} is {state}: fork() needs a live "
                f"decoding slot (step the engine until it is "
                f"decoding, then fork)")
        parent = self._slots[pidx]
        if parent.handle.request.pin_session:
            raise ValueError(
                f"{request_id} pins a session: a session continues "
                f"ONE stream — fork before pinning, or continue the "
                f"session and fork the continuation")
        free = self._free_slots()
        if not free:
            raise RuntimeError(
                f"no free slot to fork {request_id} into "
                f"(max_slots={self.max_slots}, all occupied) — retire "
                f"or prune a branch first")
        if parent.group is None:
            parent.group = next(self._fork_seq)
        kids = getattr(parent.handle, "_fork_children", None)
        branch = len(kids) + 1 if kids else 1
        now = self._clock()
        ch = self._spawn_branch(pidx, free[0], branch, now,
                                seed=seed, max_new=max_new_tokens)
        return BranchHandle(self, ch, branch)

    def prune(self, request_id):
        """Cut a fork branch (or any live/swapped request): free its
        private blocks, drop its references on shared ones, and seal a
        complete ``finish_reason="pruned"`` result carrying everything
        emitted so far — the handle resolves, never wedges.  Typed
        ValueError for a request that is not live or swapped (queued
        requests cancel by deadline; finished ones are already
        sealed)."""
        if self._closed:
            raise RuntimeError(
                "engine is closed; build a new one with model.serve()")
        now = self._clock()
        for i, s in enumerate(self._slots):
            if s is not None \
                    and s.handle.request.request_id == request_id:
                if self._c_fork_pruned is not None:
                    self._c_fork_pruned.inc()
                _trace.event("serve/prune", cat="serve",
                             request=request_id, slot=i,
                             tokens=len(s.emitted))
                self._retire(i, s, now, finish_reason="pruned")
                return
        for j, sw in enumerate(self._swapped):
            if sw.request.request_id != request_id:
                continue
            # a swapped branch holds no pool blocks (freed at
            # preempt) — sealing it is pure host bookkeeping
            n = len(sw.emitted)
            submit_t = getattr(sw.handle, "_submit_time",
                               sw.admit_time)
            result = GenerationResult(
                request_id=request_id,
                tokens=np.concatenate(
                    [sw.request.prompt_ids,
                     np.asarray(sw.emitted, np.int32)]),
                finish_reason="pruned",
                ttft=sw.first_token_time - submit_t,
                tpot=((now - sw.first_token_time) / (n - 1)
                      if n > 1 else None),
                queue_time=sw.admit_time - submit_t,
                admitted_step=sw.admitted_step,
                finished_step=self.step_count,
                branch=getattr(sw, "branch", 0),
                score=(sw.score
                       if getattr(sw, "group", None) is not None
                       else None))
            if _reqs._active:
                _reqs._ledger.on_retire(
                    request_id, engine=self.stats.engine_label,
                    t=now, finish_reason="pruned", tokens=n)
            if self._c_fork_pruned is not None:
                self._c_fork_pruned.inc()
            _trace.event("serve/prune", cat="serve",
                         request=request_id, slot=None, tokens=n)
            sw.handle._finish(result)
            self.stats.on_complete(result)
            del self._swapped[j]
            self._handles.pop(request_id, None)
            return
        raise ValueError(
            f"{request_id}: not a live or swapped request — prune() "
            f"cuts a decoding branch (queued requests expire by "
            f"deadline; finished ones are already sealed)")

    def _sched_admissions(self, navail, now):
        """One scheduler consultation, shared by the whole-prompt and
        budgeted passes so the two cannot drift: cap by the
        admission-interleave knob, pass the warm-prefix cost pricer
        when the scheduler takes one, and reject deadline-expired
        requests.  Returns the admit list."""
        if self.paged_arena is not None \
                and self.paged_arena.config.admit_per_step is not None:
            navail = min(navail,
                         self.paged_arena.config.admit_per_step)
        if self._sched_cost is not None:
            admit, expired = self.scheduler.schedule(
                navail, now, cost=self._sched_cost)
        else:
            admit, expired = self.scheduler.schedule(navail, now)
        self._reject_expired(expired, now)
        return admit

    def _blocked_priority(self):
        """The capacity-block fairness bound: a swapped request still
        waiting after the resume pass outranks fresh arrivals at or
        below its priority (it already streamed tokens — letting new
        work overtake would grow its latency without bound)."""
        return (max(sw.priority for sw in self._swapped)
                if self._swapped else None)

    def _reject_expired(self, expired, now):
        for req in expired:
            self.stats.on_deadline_expired(req.request_id)
            _trace.event("serve/request_rejected", cat="serve",
                         request=req.request_id, reason="deadline")
            if _reqs._active:
                _reqs._ledger.on_reject(req.request_id, t=now,
                                        reason="deadline",
                                        engine=self.stats.engine_label,
                                        started=False)
            self._handles.pop(req.request_id)._reject(
                DeadlineExceededError(
                    f"{req.request_id}: deadline {req.deadline} passed "
                    f"at {now} before a slot was available"))

    # -- chunked-prefill token budget (the long-context round) -----------
    def _schedule_budgeted(self, now):
        """One scheduling pass under ``prefill_token_budget``: spend
        at most that many prefill TOKENS this step — first on
        in-flight chunked prefills (admission order: the FIFO contract
        holds across steps, an expensive head request BLOCKS the
        budget, it is never skipped), then on new admissions.  A new
        admission whose prompt exceeds the remaining budget simply
        carries over: its chunks continue next step, which is the
        whole point — decode lanes dispatched BEFORE this pass
        (step() order) never wait for more than one step's budget of
        prefill work."""
        left = self._budget
        B = self.paged_arena.block_size
        for idx in sorted(self._prefilling,
                          key=lambda i: self._prefilling[i].seq):
            if left < B:
                break
            left = self._advance_prefilling(idx, left, now)
        free = self._free_slots()
        if not free and self.scheduler.queue_depth == 0:
            return
        admit = self._sched_admissions(len(free), now)
        blocked_p = self._blocked_priority()
        for k, req in enumerate(admit):
            ok = False
            admissible = (left >= B
                          and (blocked_p is None
                               or getattr(req, "priority", 0)
                               > blocked_p))
            if admissible and self._ring_eligible(
                    len(req.prompt_ids)):
                # ring prefill is ONE mesh-sharded dispatch for the
                # whole prompt — admit whole and charge the budget,
                # so no further prefill stacks onto this step
                ok = self._admit(free[0], req, now)
                if ok:
                    free.pop(0)
                    left = max(0, left - len(req.prompt_ids))
            elif admissible:
                idx = self._start_prefilling(free[0], req, now)
                if idx is not None:
                    free.pop(0)
                    ok = True
                    left = self._advance_prefilling(idx, left, now)
            if not ok:
                # budget exhausted or capacity-blocked: everything
                # scheduled from here returns to the queue FRONT in
                # original order — admission order blocks, it never
                # skips
                for r in reversed(admit[k:]):
                    self.scheduler.requeue_front(r)
                break

    def _start_prefilling(self, idx, req, now):
        """Begin a chunked-prefill admission at slot ``idx``: acquire
        any cached prefix, allocate the request's prompt blocks (all
        of them up front — a mid-prefill capacity dance would
        deadlock against other prefills), and park the request in
        ``self._prefilling`` with a fresh full-width cache row.
        Returns the slot index, or None when the blocks do not fit
        (caller requeues at the queue front)."""
        arena = self.paged_arena
        B = arena.block_size
        plen = len(req.prompt_ids)
        cache = self.prefix_cache
        _sp = _stepprof._active
        if _sp:
            _stepprof.push("admit")
        nodes = []
        if cache is not None:
            if _sp:
                _stepprof.push("prefix_lookup")
            nodes = cache.lookup(req.prompt_ids)[:(plen - 1) // B]
            if _sp:
                _stepprof.pop()
            if nodes:
                cache.acquire(nodes)
        j_lo0 = 0
        if self._window is not None:
            # a windowed admission only ever stores the lanes a
            # future query can attend: blocks below the first
            # in-window lane are never allocated at all
            j_lo0 = max(0, (plen - self._window + 1) // B)
        n_new = plen // B + 1 - j_lo0 - len(nodes)
        new_blocks = self._alloc_blocks(n_new,
                                        getattr(req, "priority", 0))
        if new_blocks is None:
            if cache is not None and nodes:
                cache.release(nodes)
            if _sp:
                _stepprof.pop()
            return None
        if _reqs._active:
            _reqs._ledger.on_admit(req.request_id,
                                   engine=self.stats.engine_label,
                                   t=now, slot=idx,
                                   step=self.step_count)
        if cache is not None:
            cache.on_admit(len(nodes), plen,
                           request_id=req.request_id)
        try:
            if nodes:
                kc_row, vc_row = cache.copy_into_row(nodes)
            else:
                # a fresh zero row of the full width — the same
                # chunk-from-scratch canonical form the int8+cache
                # cold path runs (chunked == full prefill bitwise on
                # dense rows, pinned by tests/test_prefix.py)
                kc_row, vc_row = arena.gather_row([], n_used=0)
        except Exception:
            # the copies above check fault sites (serve.prefix_copy /
            # serve.paged_copy): a raise here is BEFORE the blocks are
            # registered in self._prefilling, so _fail's sweep would
            # never see them — return them ourselves or they leak
            arena.free(new_blocks)
            if cache is not None and nodes:
                cache.release(nodes)
            raise
        ids = np.zeros((1, self.max_len), np.int32)
        ids[0, :plen] = req.prompt_ids
        pf = _Prefilling()
        pf.handle = self._handles[req.request_id]
        pf.request = req
        pf.ids_j = jnp.asarray(ids)
        pf.kc_row, pf.vc_row = kc_row, vc_row
        pf.hidden = None
        pf.off = len(nodes) * B
        pf.last_off = ((plen - 1) // B) * B
        if self._window is not None:
            pf.blocks = [arena.trash] * j_lo0 + new_blocks
        else:
            pf.blocks = [n.block for n in nodes] + new_blocks
        pf.n_shared = len(nodes)
        pf.nodes = nodes
        pf.key0 = jax.random.split(
            jax.random.PRNGKey(int(req.seed)), 1)[0]
        pf.temp = np.float32(req.temperature)
        pf.t_admit = now
        pf.admitted_step = self.step_count
        pf.seq = next(self._prefill_seq)
        self._prefilling[idx] = pf
        _trace.event("serve/prefill_budgeted", cat="serve",
                     request=req.request_id, slot=idx,
                     prompt_len=plen, step=self.step_count,
                     chunks=(pf.last_off - pf.off) // B + 1)
        if _sp:
            _stepprof.pop()
        return idx

    def _advance_prefilling(self, idx, left, now):
        """Spend up to ``left`` budget tokens on slot ``idx``'s
        chunked prefill (block-width ``_chunk_row`` windows — the
        exact executable warm admission rides, so a budgeted stream
        is byte-identical to an unbudgeted one).  Completes the
        admission when the last chunk lands.  Returns the remaining
        budget."""
        pf = self._prefilling[idx]
        B = self.paged_arena.block_size
        rid = pf.request.request_id
        while left >= B and pf.off <= pf.last_off:
            if _faults._armed:
                # chaos hook: a fault BETWEEN chunks models a raising
                # mid-prefill dispatch — step() fails the engine
                # typed, the rejection is started=False (nothing
                # streamed), and _fail returns the partial blocks to
                # the free list (RESILIENCE.md; chaos_longctx)
                _faults.check("serve.prefill_chunk")
            pf.hidden, pf.kc_row, pf.vc_row = self._x.chunk_row(
                self._params, pf.ids_j, pf.kc_row, pf.vc_row,
                jnp.int32(pf.off))
            self._c_budget_chunks.inc()
            if _reqs._active:
                _reqs._ledger.on_prefill_chunk(
                    rid, engine=self.stats.engine_label,
                    t=self._clock(), offset=pf.off)
            pf.off += B
            left -= B
        if pf.off > pf.last_off:
            self._finish_prefilling(idx, pf)
        return left

    def _finish_prefilling(self, idx, pf):
        """The last chunk landed: sample the admission token from the
        final chunk's hidden block (mirrors ``_prefill_one``'s tail
        via ``_first_from_hidden`` — bitwise the unbudgeted token),
        scatter the row's lanes into the request's pool blocks, and
        promote the reservation to a LIVE slot."""
        arena = self.paged_arena
        req = pf.request
        plen = len(req.prompt_ids)
        ast0 = mask0 = None
        if req.structured is not None:
            # budgeted admission of a structured request: the first
            # token samples here, so the initial mask applies here
            ast0 = req.structured.initial()
            mask0 = jnp.asarray(
                np.asarray(req.structured.mask(ast0), bool))
        tok0, carry_key = _first_from_hidden(
            self._params, pf.hidden,
            jnp.int32(plen - 1 - pf.last_off), pf.key0, pf.temp,
            self._top_p, top_k=self._statics["top_k"],
            use_top_p=self._statics["use_top_p"], mask=mask0)
        lanes = {j: pf.blocks[j]
                 for j in range(pf.n_shared, plen // arena.block_size
                                + 1)
                 if pf.blocks[j] != arena.trash}
        arena.scatter_row(pf.kc_row, pf.vc_row, lanes)
        if self.draft is not None:
            # the draft prefills whole at completion — it is cheap by
            # construction (the whole point of a draft), so it never
            # needed the budget's protection
            dkc_row, dvc_row = _prefill_rows(
                self._d_params, pf.ids_j, *self._d_statics,
                quant=self._quant)
            self._dkc, self._dvc = _write_slot(
                self._dkc, self._dvc, dkc_row, dvc_row,
                jnp.int32(idx))
        self.stats.on_prefill()
        slot = _Slot(pf.handle, req.max_new_tokens, pf.t_admit,
                     pf.admitted_step)
        slot.prefix_nodes = pf.nodes
        slot.blocks = pf.blocks
        slot.n_shared = pf.n_shared
        slot.automaton = req.structured
        slot.astate = ast0
        del self._prefilling[idx]
        self._slots[idx] = slot
        tok0 = int(np.asarray(tok0))   # device sync: prefill done
        t_first = self._clock()
        submit_t = getattr(pf.handle, "_submit_time", pf.t_admit)
        self.stats.on_admission(pf.t_admit - submit_t,
                                t_first - pf.t_admit,
                                warm=bool(pf.nodes))
        if _reqs._active:
            _reqs._ledger.on_first_token(
                req.request_id, engine=self.stats.engine_label,
                t=t_first)
        self._toks[idx] = tok0
        self._pos[idx] = plen
        self._temps[idx] = pf.temp
        self._keys = self._keys.at[idx].set(carry_key)
        self._emit(idx, slot, tok0, t_first)

    # -- ring-attention prefill (the long-context round, part 3) ---------
    def _ring_width(self, plen):
        """The padded prompt width a ring prefill runs at: the
        smallest width that is both a block multiple (the scatter's
        lane granularity) and divisible by the mesh width (equal
        per-shard sequence chunks), or None when that exceeds
        ``max_len`` (the caller falls back to the serial prefill)."""
        B = self.paged_arena.block_size
        tpw = self.tp_exec.tp
        # the admission scatters plen//B + 1 lanes (the last one is
        # the block the first decode write lands in — same as the
        # serial narrow path), so the row must be at least that wide
        wn0 = (plen // B + 1) * B
        step = B * tpw // math.gcd(B, tpw)
        wn = -(-wn0 // step) * step
        return wn if wn <= self.max_len else None

    def _ring_eligible(self, plen):
        """Ring prefill fires for cold admissions at or above
        ``TPConfig.ring_min_tokens`` when a legal padded width
        exists."""
        if not self._ring:
            return False
        mt = getattr(self._tp_cfg, "ring_min_tokens", 0) or 0
        return plen >= mt and self._ring_width(plen) is not None

    def _prefill_cost(self, req):
        """Scheduler interleave price of admitting ``req`` now: 0 for
        a warm prefix hit that recomputes at most one block-width
        chunk, 1 for anything colder (the O(ctx²) work the interleave
        cap exists to bound)."""
        cache = self.prefix_cache
        plen = len(req.prompt_ids)
        if _stepprof._active:
            _stepprof.push("prefix_lookup")
            usable = min(len(cache.lookup(req.prompt_ids)),
                         (plen - 1) // cache.block_size)
            _stepprof.pop()
        else:
            usable = min(len(cache.lookup(req.prompt_ids)),
                         (plen - 1) // cache.block_size)
        if usable > 0 and plen - usable * cache.block_size \
                <= cache.block_size:
            return 0
        return 1

    def _prefill_admissions(self, reqs):
        """One batched prefill dispatch for a scheduling pass's cold
        paged admissions (:func:`_prefill_batch`): all R requests ride
        one (R, W) executable at the pass's shared narrow width (the
        largest per-request block-multiple width — rows are bitwise
        invariant to extra pad width, so sharing the widest is free)
        and ONE host sync fetches every first token.  Returns
        ``{request_id: (tok0, batch row index)}``; the stacked rows
        and carried keys stay on the device in ``self._admit_batch``
        for the deferred per-request writes to flush against."""
        B = self.paged_arena.block_size
        wn = min(self.max_len,
                 max((len(r.prompt_ids) // B + 1) * B for r in reqs))
        R = len(reqs)
        ids = np.zeros((R, wn), np.int32)
        plens = np.zeros(R, np.int32)
        seeds = np.zeros(R, np.int32)
        temps = np.zeros(R, np.float32)
        for r, req in enumerate(reqs):
            plen = len(req.prompt_ids)
            ids[r, :plen] = req.prompt_ids
            plens[r] = plen
            seeds[r] = int(req.seed)
            temps[r] = req.temperature
        tok0, keys, kc, vc = self._x.prefill_batch(
            self._params, jnp.asarray(ids), jnp.asarray(plens),
            jnp.asarray(seeds), jnp.asarray(temps), self._top_p)
        tok0 = np.asarray(tok0)      # ONE sync for the whole pass
        # rows stay STACKED on the device: per-request scatters and
        # key writes are deferred against this batch and flushed as
        # one dispatch each at the end of the pass
        # (_flush_admission_writes) — per-admission device work
        # inside the pass drops to zero
        self._admit_batch = (keys, kc, vc)
        return {req.request_id: (int(tok0[r]), r)
                for r, req in enumerate(reqs)}

    def _flush_admission_writes(self, drop_batch=False):
        """Write one scheduling pass's deferred admission state: ONE
        batched pool scatter (``arena.scatter_rows``) for every
        admitted request's prefilled lanes and ONE key-table write
        for their carried sampling keys.  Called at the end of
        ``_schedule`` (``drop_batch=True`` — the pass is over) and
        defensively before any same-pass path that reads pool or key
        state a deferred write still owns (preemption's swap gather,
        block frees on instant retire/reject — a freed block could be
        re-allocated and the late flush would then clobber the new
        owner)."""
        if self._pending_scatter:
            _, kc_b, vc_b = self._admit_batch
            self.paged_arena.scatter_rows(
                kc_b, vc_b,
                [r for r, _ in self._pending_scatter],
                [l for _, l in self._pending_scatter])
            self._pending_scatter = []
        if self._pending_keys:
            keys_b = self._admit_batch[0]
            idxs = jnp.asarray(np.asarray(
                [i for i, _ in self._pending_keys], np.int32))
            rs = jnp.asarray(np.asarray(
                [r for _, r in self._pending_keys], np.int32))
            self._keys = _merge_keys(self._keys, keys_b, idxs, rs)
            self._pending_keys = []
        if drop_batch:
            self._admit_batch = None

    def _admit(self, idx, req, now, prefilled=None):
        """Prefill one request into slot ``idx`` and emit its first
        token.  Mirrors the offline key chain exactly: generate() makes
        per-row keys with split(PRNGKey(seed), B)[row]; a single-prompt
        call is B=1, row 0.  ``prefilled``: this request's
        ``(tok0, batch row index)`` from a BATCHED pass prefill
        (:meth:`_prefill_admissions`) — the cache rows and carried
        key stay STACKED in ``self._admit_batch`` and the writes
        defer onto that batch (cold paged admissions only, so the
        warm/draft branches below never see it).

        With a prefix cache, the longest cached block-prefix is copied
        into the slot and only the suffix past the divergence boundary
        is prefilled (block-width chunks through ``_chunk_row``).
        Cached K/V is canonical prefill output and the first-token
        sampling mirrors ``_prefill_one``'s tail, so warm token
        streams are byte-identical to the cold path's.  The match is
        capped at ``(plen - 1) // block_size`` blocks: the hidden
        state at prompt_len-1 must be recomputed to sample from — a
        fully-cached prompt still recomputes its last block."""
        handle = self._handles[req.request_id]
        plen = len(req.prompt_ids)
        cache = self.prefix_cache
        _sp = _stepprof._active
        if _sp:
            _stepprof.push("admit")
        nodes = []
        if cache is not None:
            if _sp:
                _stepprof.push("prefix_lookup")
            nodes = cache.lookup(req.prompt_ids)[
                :(plen - 1) // cache.block_size]
            if _sp:
                _stepprof.pop()
        arena = self.paged_arena
        new_blocks = []
        if arena is not None:
            # admission by BLOCKS FREE: the request needs lanes
            # [len(nodes), plen//B] now (matched prefix blocks are
            # shared by reference — zero copy).  The matched path is
            # ACQUIRED before allocating: _alloc_blocks' eviction only
            # spares referenced nodes, so without the pin the
            # allocation could evict the request's OWN match and hand
            # the same pool block back as one of new_blocks — the
            # block table would alias one block in two lanes and the
            # admission scatter would corrupt the shared prefix KV.
            # Eviction and strictly-lower priority preemption run
            # inside _alloc_blocks; a miss blocks admission (caller
            # requeues at the queue front) rather than dropping the
            # request
            if cache is not None and nodes:
                cache.acquire(nodes)
            j_lo0 = 0
            if self._window is not None:
                # windowed admission: lanes below the first in-window
                # position are never attended by any future query, so
                # their blocks are never allocated — a long prompt on
                # a windowed model admits in O(window) blocks
                j_lo0 = max(0,
                            (plen - self._window + 1)
                            // arena.block_size)
            n0 = plen // arena.block_size + 1
            new_blocks = self._alloc_blocks(
                n0 - j_lo0 - len(nodes), getattr(req, "priority", 0))
            if new_blocks is None:
                if cache is not None and nodes:
                    cache.release(nodes)
                if _sp:
                    _stepprof.pop()
                return False
        if _reqs._active:
            # admission started: the queue-wait phase of this hop ends
            # HERE (cold/warm classification is annotated by the
            # prefix cache's own hook below)
            _reqs._ledger.on_admit(req.request_id,
                                   engine=self.stats.engine_label,
                                   t=now, slot=idx,
                                   step=self.step_count)
        ast0 = mask0 = None
        if req.structured is not None:
            # structured decoding: the FIRST token samples inside the
            # prefill executable, so the initial state's vocab mask
            # threads into it (fixed (vocab,) shape — no new
            # signature per grammar)
            ast0 = req.structured.initial()
            mask0 = jnp.asarray(
                np.asarray(req.structured.mask(ast0), bool))
        with _trace.span("serve/prefill", cat="serve",
                         request=req.request_id, slot=idx,
                         prompt_len=plen, step=self.step_count,
                         cached_tokens=(len(nodes) * cache.block_size
                                        if cache is not None else 0)):
            ids_j = None
            if prefilled is None:
                ids = np.zeros((1, self.max_len), np.int32)
                ids[0, :plen] = req.prompt_ids
                ids_j = jnp.asarray(ids)
                key0 = jax.random.split(
                    jax.random.PRNGKey(int(req.seed)), 1)[0]
            temp = np.float32(req.temperature)
            # int8 + prefix cache: EVERY admission (cold included)
            # runs the chunked path, because a quantized engine's
            # full-prefill hidden attends FLOAT keys while a chunked
            # recompute over the quantized cache attends DEQUANTIZED
            # ones — the streams can only be byte-identical if cold
            # and warm admissions share one canonical form, and
            # chunked-quantized is the one donation can store (docs/
            # SERVING.md "int8 and the prefix cache")
            deferred_row = None
            if prefilled is not None:
                # batched-pass fast path (_prefill_admissions): this
                # request's prefill — key chain included — already ran
                # in ONE dispatch for the whole scheduling pass, and
                # its row stays in the stacked device batch: the
                # scatter and key write below DEFER onto it (one
                # flushed dispatch each per pass), so admitting K
                # requests costs the live decode lanes one write, not K
                tok0, deferred_row = prefilled
                carry_key = kc_row = vc_row = None
            elif nodes or (cache is not None and self._quant):
                tok0, carry_key, kc_row, vc_row = self._admit_warm(
                    ids, plen, nodes, key0, temp,
                    rid=req.request_id, mask=mask0)
            elif arena is not None and self._ring_eligible(plen) \
                    and req.structured is None:
                # ring-attention prefill (the long-context round):
                # the prompt's sequence axis shards over the tp mesh
                # and K/V blocks rotate the ICI ring
                # (parallel/ring_attention.py via the executor seam)
                # — ONE dispatch whose attention workspace per shard
                # is O((S/tp)^2) instead of O(S^2), for prompts
                # beyond one shard's flash tile.  Token-identical to
                # the serial prefill (logsumexp merge reorders the
                # float reduction — same caveat as the TP psum),
                # pinned by tests/test_serve_longctx.py.
                wn = self._ring_width(plen)
                tok0, carry_key, kc_row, vc_row = \
                    self.tp_exec.ring_prefill_one(
                        self._params, ids_j[:, :wn], plen, key0,
                        temp, self._top_p)
            else:
                pf_ids = ids_j
                if arena is not None:
                    # narrow-width admission (the gather-tax round):
                    # prefill at the smallest block-multiple width
                    # whose lanes cover the blocks this admission
                    # scatters, not max_len — prefill cost tracks the
                    # PROMPT's length, so a burst of short admissions
                    # stops stalling the decode lanes behind
                    # O(max_len) pad work (the paged bench's TPOT
                    # tax).  Prefill rows are bitwise invariant to
                    # the padded width (every op is row-independent
                    # over positions; pinned by
                    # tests/test_paged.py::test_prefill_width_bitwise
                    # _invariance), so streams are unchanged.  One
                    # executable per distinct width, bounded by
                    # max_len // block_size — the warmup pass covers
                    # the workload's widths, keeping the recompile
                    # pin intact
                    wn = min(self.max_len,
                             (plen // arena.block_size + 1)
                             * arena.block_size)
                    pf_ids = ids_j[:, :wn]
                tok0, carry_key, kc_row, vc_row = self._x.prefill_one(
                    self._params, pf_ids, plen, key0, temp,
                    self._top_p,
                    **({"mask": mask0} if mask0 is not None else {}))
            if arena is not None:
                # the prefilled lanes past the shared prefix scatter
                # into the request's freshly-allocated pool blocks;
                # matched lanes never move (shared by reference).
                # Windowed admissions start at the first in-window
                # lane instead (below it nothing was allocated)
                m = len(nodes) + j_lo0
                lanes = {m + j: b for j, b in enumerate(new_blocks)}
                if deferred_row is not None:
                    self._pending_scatter.append((deferred_row, lanes))
                else:
                    arena.scatter_row(kc_row, vc_row, lanes)
            else:
                self._kc, self._vc = self._x.write_slot(
                    self._kc, self._vc, kc_row, vc_row,
                    jnp.int32(idx))
            if self.draft is not None:
                # the draft sees the SAME prompt cold (its prefill is
                # cheap by construction; the prefix cache stores only
                # target K/V) — rows land in the draft arena at the
                # same slot so the spec step advances both in lockstep
                dkc_row, dvc_row = _prefill_rows(
                    self._d_params, ids_j, *self._d_statics,
                    quant=self._quant)
                self._dkc, self._dvc = _write_slot(
                    self._dkc, self._dvc, dkc_row, dvc_row,
                    jnp.int32(idx))
        if cache is not None:
            if arena is None:
                # paged admissions acquired the path BEFORE the block
                # allocation above; acquiring again would double-pin
                cache.acquire(nodes)
            cache.on_admit(len(nodes), plen,
                           request_id=req.request_id)
        self.stats.on_prefill()
        slot = _Slot(handle, req.max_new_tokens, now, self.step_count)
        slot.prefix_nodes = nodes
        slot.automaton = req.structured
        slot.astate = ast0
        if arena is not None:
            slot.blocks = ([n.block for n in nodes]
                           + [arena.trash] * j_lo0 + new_blocks)
            slot.n_shared = len(nodes)
        self._slots[idx] = slot
        tok0 = int(np.asarray(tok0))  # device sync: prefill is done
        t_first = self._clock()
        self.stats.on_admission(
            now - getattr(handle, "_submit_time", now),
            t_first - now, warm=bool(nodes))
        if _reqs._active:
            _reqs._ledger.on_first_token(req.request_id,
                                         engine=self.stats.engine_label,
                                         t=t_first)
        self._toks[idx] = tok0
        self._pos[idx] = plen
        self._temps[idx] = temp
        if deferred_row is not None:
            self._pending_keys.append((idx, deferred_row))
        else:
            self._keys = self._keys.at[idx].set(carry_key)
        self._emit(idx, slot, tok0, t_first)
        if _sp:
            _stepprof.pop()
        return True

    def _admit_warm(self, ids, plen, nodes, key0, temp, rid=None,
                    mask=None):
        """Warm admission: one gather copies the matched blocks into a
        fresh cache row, then block-width ``_chunk_row`` calls prefill
        [divergence, last-block-end) — fixed shapes throughout, so the
        jit cache stays warm whatever the hit length."""
        cache = self.prefix_cache
        B = cache.block_size
        kc_row, vc_row = cache.copy_into_row(nodes)
        ids_j = jnp.asarray(ids)
        last_off = ((plen - 1) // B) * B
        off = len(nodes) * B
        hidden = None
        while off <= last_off:
            hidden, kc_row, vc_row = self._x.chunk_row(
                self._params, ids_j, kc_row, vc_row, jnp.int32(off))
            if _reqs._active and rid is not None:
                _reqs._ledger.on_prefill_chunk(
                    rid, engine=self.stats.engine_label,
                    t=self._clock(), offset=off)
            off += B
        tok0, carry_key = _first_from_hidden(
            self._params, hidden, jnp.int32(plen - 1 - last_off),
            key0, temp, self._top_p, top_k=self._statics["top_k"],
            use_top_p=self._statics["use_top_p"], mask=mask)
        return tok0, carry_key, kc_row, vc_row

    # -- disaggregated prefill / KV shipping (the disagg round) ----------
    # The fleet drives these from OUTSIDE the step loop: a prefill
    # specialist builds the shippable canonical-KV prefix of a prompt
    # (chunked — the PR-12 budget machinery's executable, so the
    # shipped bytes ARE the canonical form warm admission consumes,
    # dense and int8 alike), exports it as a versioned host image
    # (serve/kvimage.py — the swap format), and a decode replica
    # adopts the image's blocks into its OWN radix tree so the
    # subsequent engine.submit lands as a local warm hit.  Parity is
    # inherited, not re-proven: warm == cold is already pinned per
    # engine, and the image is a byte copy of canonical chunk KV.

    def _require_ship_support(self):
        if self._closed:
            raise RuntimeError(
                "engine is closed; build a new one with model.serve()")
        if self._failed:
            raise EngineFailedError(
                "engine has failed; rebuild it (EngineSupervisor does "
                "this automatically)", engine_step=self.step_count)
        if self.paged_arena is None or self.prefix_cache is None:
            raise RuntimeError(
                "KV shipping needs paged= AND prefix_cache= on every "
                "replica: the ship format is the paged host image and "
                "residency lives in the radix tree (docs/SERVING.md "
                "'Disaggregated serving')")

    def start_prefix_build(self, prompt_ids):
        """Begin building the shippable prefix of ``prompt_ids``: its
        ``(plen - 1) // block_size`` full blocks (the cap warm
        admission applies — the final partial block is always
        recomputed by the admitting engine to sample from).  Returns a
        :class:`_PrefixJob`, or None when nothing is shippable (short
        prompt).  A prefix already resident in THIS engine's tree
        starts complete (``hit`` set — no recompute, the fleet's
        shared-prefix-hit path); the matched path is ACQUIRED until
        the job is exported or abandoned."""
        self._require_ship_support()
        arena, cache = self.paged_arena, self.prefix_cache
        B = arena.block_size
        toks = np.asarray(prompt_ids, np.int32).reshape(-1)
        plen = len(toks)
        n_goal = (plen - 1) // B
        if n_goal < 1:
            return None
        job = _PrefixJob()
        job.tokens = toks
        job.plen = plen
        job.n_goal = n_goal
        job.engine = self
        nodes = cache.lookup(toks)[:n_goal]
        cache.acquire(nodes)
        job.nodes = nodes
        job.hit = len(nodes) == n_goal
        job.off = len(nodes) * B
        job.last_off = (n_goal - 1) * B
        job.ids_j = None
        job.kc_row = job.vc_row = None
        if job.hit:
            return job
        try:
            ids = np.zeros((1, self.max_len), np.int32)
            ids[0, :plen] = toks
            job.ids_j = jnp.asarray(ids)
            if nodes:
                job.kc_row, job.vc_row = cache.copy_into_row(nodes)
            else:
                # the fresh-zero chunk-from-scratch canonical form —
                # the same row every cold chunked admission starts
                # from
                job.kc_row, job.vc_row = arena.gather_row([],
                                                          n_used=0)
        except Exception:
            # the copies check fault sites (serve.prefix_copy /
            # serve.paged_copy): a raise here happens before the job
            # reaches the caller, so nothing would ever release the
            # acquired path — release it ourselves or the refs pin
            # those blocks unevictable forever (the same guard the
            # warm-admission path keeps)
            self.abandon_prefix_build(job)
            raise
        return job

    def advance_prefix_build(self, job, max_tokens=None, rid=None):
        """Spend up to ``max_tokens`` prefill tokens on the build's
        chunk windows (None = finish it; the fleet passes the
        specialist's ``prefill_token_budget`` so one giant document
        never monopolizes a specialist's step).  Returns True when
        the build is complete.  A raising chunk FAILS THE ENGINE
        typed — the same contract as a raising admission prefill
        inside ``step()`` — which is what makes 'kill a prefill
        specialist mid-ship' a first-class chaos scenario."""
        self._require_ship_support()
        if job.engine is not self:
            # a supervisor rebuild happened under the job: its rows /
            # nodes belong to the dead engine's arena — advancing
            # would adopt the wrong blocks.  The fleet restarts the
            # build (nothing streamed; the replay is identical)
            raise RuntimeError(
                "stale prefix build: the engine was rebuilt under it;"
                " restart the build")
        B = self.paged_arena.block_size
        left = (job.last_off - job.off + B if max_tokens is None
                else int(max_tokens))
        # a prefill specialist never runs the decode step loop, so its
        # anatomy comes from here: each budgeted advance is one step
        # quantum (no-op when a step is already open — a build driven
        # from inside step() stays attributed to that step)
        quantum = (_stepprof.begin_quantum(self.stats.engine_label,
                                           step=self.step_count)
                   if _stepprof._active else False)
        try:
            while left >= B and job.off <= job.last_off:
                if _faults._armed:
                    _faults.check("serve.prefill_chunk")
                off = job.off
                _, job.kc_row, job.vc_row = self._x.chunk_row(
                    self._params, job.ids_j, job.kc_row, job.vc_row,
                    jnp.int32(off))
                job.off += B
                left -= B
                if _reqs._active and rid is not None:
                    if quantum:
                        _stepprof.push("ledger")
                    _reqs._ledger.on_prefill_chunk(
                        rid, engine=self.stats.engine_label,
                        t=self._clock(), offset=off)
                    if quantum:
                        _stepprof.pop()
        except Exception as e:
            if quantum:
                _stepprof.abort()
            self.abandon_prefix_build(job)
            raise self._fail(e) from e
        if quantum:
            _stepprof.end()
        return job.off > job.last_off

    def abandon_prefix_build(self, job):
        """Release a build's acquired prefix refs (ship fallback,
        failover, a raising chunk).  Idempotent; a job whose engine
        was rebuilt is a no-op (the old tree died with it)."""
        if job.nodes and self.prefix_cache is not None \
                and job.engine is self:
            try:
                self.prefix_cache.release(job.nodes)
            except RuntimeError:
                pass
        job.nodes = []

    def export_prefix_image(self, job):
        """Finish the source half of a ship: DONATE the finished
        chunk row's blocks into this engine's radix tree (residency —
        the next request for this prefix exports without recompute,
        fleet-wide) and pack the narrow versioned host image
        (``serve.kv_ship`` fault site).  Under pool pressure the
        donation is skipped (counted by the cache) and the image
        ships straight from the row — shipping never fails on SOURCE
        capacity.  Returns ``(image, resident)``: ``resident`` says
        whether this engine's tree now holds the prefix (the fleet
        records residency only when it is true — a skipped donation
        must not plant a stale index entry).  Releases the job's
        refs in all cases."""
        self._require_ship_support()
        if job.engine is not self:
            raise RuntimeError(
                "stale prefix build: the engine was rebuilt under it;"
                " restart the build")
        arena, cache = self.paged_arena, self.prefix_cache
        n = job.n_goal
        try:
            if job.hit:
                # resident: export straight from the tree's blocks
                return arena.export_image(
                    [nd.block for nd in job.nodes], n), True
            k = len(job.nodes)
            new = arena.alloc(n - k)
            if new is None:
                cache.on_donate_skipped(n - k)
                return arena.export_row_image(job.kc_row, job.vc_row,
                                              n), False
            try:
                arena.scatter_row(job.kc_row, job.vc_row,
                                  {k + j: b for j, b in enumerate(new)})
                blockmap = [nd.block for nd in job.nodes] + new
                path = cache.adopt_blocks(job.tokens, blockmap, n)
            except Exception:
                arena.free(new)
                raise
            adopted = {nd.block for nd in path}
            arena.free([b for b in new if b not in adopted])
            return arena.export_image(
                [nd.block for nd in path], n), True
        finally:
            self.abandon_prefix_build(job)

    def admit_prefix_image(self, tokens, image):
        """Destination half of a ship: validate the image TYPED
        (:class:`~singa_tpu.serve.kvimage.KVImageError` — a truncated
        or geometry-mismatched image never scatters), land its lanes
        in this pool, and ADOPT them into the radix tree so the next
        admission of ``tokens`` is a local warm hit.  Returns the
        ACQUIRED node path (the caller releases it once the shipped
        request resolves — the blocks must survive until admission),
        or None when the pool has no capacity for the missing blocks
        (cold fallback, counted by the fleet, never an error)."""
        self._require_ship_support()
        arena, cache = self.paged_arena, self.prefix_cache
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = int(image.n_data)
        existing = cache.lookup(toks)[:n]
        k = len(existing)
        if k == n:
            # already resident (an earlier ship, or a sibling's
            # donation): nothing will scatter, so run the typed
            # validation HERE (the scatter path's lives inside
            # arena.import_image — exactly one validate either way)
            image.validate(arena.block_size, arena.quant,
                           pool_k=arena.pool_k)
            cache.touch(existing)
            cache.acquire(existing)
            return existing
        # pin the partial hit across the allocation: alloc's LRU
        # eviction must not reclaim the very prefix we are extending
        cache.acquire(existing)
        new = arena.alloc(n - k)
        if new is None:
            cache.release(existing)
            return None
        try:
            arena.import_image(image,
                               {k + j: b for j, b in enumerate(new)})
            blockmap = [nd.block for nd in existing] + new
            path = cache.adopt_blocks(toks, blockmap, n)
        except Exception:
            arena.free(new)
            cache.release(existing)
            raise
        adopted = {nd.block for nd in path}
        arena.free([b for b in new if b not in adopted])
        cache.release(existing)
        cache.acquire(path)
        return path
