"""singa_tpu.serve — continuous-batching inference engine (round 6).

The serving half of the north star: asynchronous generation requests
flow through a FIFO scheduler into a fixed-shape slot pool and advance
one token per engine iteration, with finished rows retired and their
slots backfilled the same step.  See docs/SERVING.md for the
architecture and engine.py for the design rationale.

Entry points::

    from singa_tpu.serve import InferenceEngine, GenerationRequest
    eng = model.serve(max_slots=8)            # == InferenceEngine(model)
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=32))
    eng.run_until_complete()
    h.result().tokens
"""

from .engine import InferenceEngine  # noqa: F401
from .request import (DeadlineExceededError, GenerationRequest,  # noqa: F401
                      GenerationResult, QueueFullError, RequestHandle)
from .scheduler import FIFOScheduler  # noqa: F401
from .stats import EngineStats  # noqa: F401
