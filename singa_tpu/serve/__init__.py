"""singa_tpu.serve — continuous-batching inference engine (round 6).

The serving half of the north star: asynchronous generation requests
flow through a FIFO scheduler into a fixed-shape slot pool and advance
one token per engine iteration, with finished rows retired and their
slots backfilled the same step.  See docs/SERVING.md for the
architecture and engine.py for the design rationale.

Since the resilience round the engine fails TYPED instead of wedging
(``EngineFailedError`` for every in-flight/queued request), and
``EngineSupervisor`` rebuilds a failed engine, requeues never-started
requests, enforces a restart budget, and sheds lowest-priority work
under SLO pressure (``LoadShedError``).  See docs/RESILIENCE.md.

Entry points::

    from singa_tpu.serve import InferenceEngine, GenerationRequest
    eng = model.serve(max_slots=8)            # == InferenceEngine(model)
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=32))
    eng.run_until_complete()
    h.result().tokens

    from singa_tpu.serve import EngineSupervisor
    sup = EngineSupervisor(model, max_slots=8, restart_budget=2)

Since the fleet round, ``ServeFleet`` puts N supervised replicas
behind a health-checked ``Router`` (least-loaded / SLO-headroom
scoring, sticky sessions, cross-replica failover with requeue parity,
optional hedging)::

    from singa_tpu.serve import ServeFleet
    fleet = ServeFleet(model, replicas=2, max_slots=4)

Since the paged round, ``paged=PagedConfig(...)`` replaces the
worst-case slot arena with ONE block-paged KV pool shared with the
prefix cache: admission by blocks free, block-by-block growth,
priority preemption with byte-exact swap/resume
(``scheduler="priority"``), zero-copy donation.  Token streams stay
bitwise identical to the slot engine's.  See docs/SERVING.md
"Paged KV and preemption"::

    from singa_tpu.serve import PagedConfig
    eng = model.serve(max_slots=16, scheduler="priority",
                      paged=PagedConfig(block_size=16, num_blocks=256),
                      prefix_cache=PrefixCacheConfig(block_size=16))

Since the TP round, ``tp=k`` shards ONE engine's weights and KV
memory across a k-device mesh (Megatron column/row layout under
``shard_map``, one psum per attention output and MLP fc2, the paged
pool sliced per shard on the H_kv axis) — models bigger than one
device, token streams pinned identical to the single-device engine.
Composes with everything above; ``serve_fleet(tp=k, replicas=n)``
partitions the mesh into n disjoint k-wide groups.  See
docs/SERVING.md "Tensor-parallel serving"::

    eng = model.serve(max_slots=8, tp=2,
                      paged=PagedConfig(block_size=16, num_blocks=256))

Since the EP/PP round the serve stack covers every architecture the
training side builds: ``ep=EPConfig(ep=, tp=)`` serves MoE models
expert-parallel (experts sharded over an ``ep`` mesh axis,
capacity-bounded GShard dispatch inside the jitted pool steps, dense
layers Megatron over an orthogonal ``tp`` axis — serve/ep.py), and
``pp=PPConfig(stages=, microbatches=)`` serves models DEEPER than one
device's memory pipeline-parallel (layers partitioned into stages,
each stage owning its layer slice of the paged KV pool, microbatched
decode so bubbles amortize across the continuous batch — serve/pp.py).
See docs/SERVING.md "Expert-parallel and pipeline serving"::

    eng = moe_model.serve(max_slots=8, ep=EPConfig(ep=2, tp=2),
                          paged=PagedConfig(block_size=16))
    eng = deep_model.serve(max_slots=8, pp=PPConfig(stages=2),
                           paged=PagedConfig(block_size=16))

Since the disaggregation round, ``roles=`` splits a fleet
DistServe-style into prefill and decode specialists: long admissions
build their canonical-KV prefix on a specialist and SHIP the blocks
to a decode replica as a versioned host image (``serve.kvimage`` —
the same format preemption swap uses), landing as a local warm hit;
the radix prefix cache becomes a fleet-level resource
(``FleetPrefixIndex``).  Streams stay byte-identical to the
single-engine oracle.  See docs/SERVING.md "Disaggregated serving"::

    fleet = model.serve_fleet(
        replicas=4, roles=("prefill", "prefill", "decode", "decode"),
        paged=PagedConfig(block_size=16, num_blocks=96),
        prefix_cache=PrefixCacheConfig(block_size=16))

Since the fork round, live KV forks copy-on-write on the paged pool:
``GenerationRequest(n=4)`` decodes 4 branches off ONE prompt (every
prompt block shared, per-branch tails allocated on first divergent
write) and returns a ``ForkHandle`` whose ``best()`` ranks branches
by cumulative chosen-token logprob; any live streaming handle can
``fork()``/``prune()`` mid-generation for tree-shaped search; and
``structured=JsonSchemaAutomaton(schema, vocab)`` constrains every
emitted token to a JSON-schema grammar via per-slot vocab masks
applied inside the jitted sample (recompiles stay 0).  See
docs/SERVING.md "Parallel sampling and structured output"::

    h = eng.submit(GenerationRequest(prompt, n=4, temperature=0.8,
                                     max_new_tokens=32))
    eng.run_until_complete()
    h.best().tokens                       # highest-scoring branch
"""

from .engine import InferenceEngine  # noqa: F401
from .fork import BranchHandle, ForkHandle  # noqa: F401
from .structured import (JsonSchemaAutomaton,  # noqa: F401
                         TokenAutomaton)
from .fleet import Router, ServeFleet  # noqa: F401
from .dist import DistFleet, ModelSpec, gpt2_spec  # noqa: F401
from .autoscale import AutoscaleConfig, Autoscaler  # noqa: F401
from .kvimage import KVImage, KVImageError  # noqa: F401
from .paged import PagedConfig, PagedKVArena  # noqa: F401
from .tp import TPConfig, TPExecutor  # noqa: F401
from .ep import EPConfig, EPExecutor  # noqa: F401
from .pp import PPConfig, PPExecutor  # noqa: F401
from .prefix import (FleetPrefixIndex, PrefixCache,  # noqa: F401
                     PrefixCacheConfig, SessionHandle)
from .request import (DeadlineExceededError, EngineFailedError,  # noqa: F401
                      FleetDownError, GenerationRequest,
                      GenerationResult, LoadShedError, QueueFullError,
                      RequestHandle, RestartBudgetExceededError)
from .scheduler import FIFOScheduler, PriorityScheduler  # noqa: F401
from .stats import EngineStats  # noqa: F401
from .supervisor import EngineSupervisor  # noqa: F401
