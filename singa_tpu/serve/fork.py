"""Caller-side handles for copy-on-write KV forking (the fork round).

Two shapes of parallel decoding ride on the same engine mechanism
(``ServeEngine._spawn_branch``: clone a live slot's block table, bump
the paged arena's refcount on every shared block, copy-on-first-write
when a branch reaches a block a sibling still references):

* **Best-of-n** — ``GenerationRequest(n=4)`` returns a
  :class:`ForkHandle`.  The engine admits the prompt ONCE, then forks
  n-1 sibling branches off the freshly admitted slot inside the same
  scheduler pass; all n branches share every prompt block and each
  accumulates the cumulative log-probability of its chosen tokens
  under the raw model distribution (``GenerationResult.score``).
  ``ranked()``/``best()`` order completed branches by that score.
* **Tree search** — any live streaming handle can be forked again
  mid-generation (``BranchHandle.fork()``), and losing branches cut
  with ``prune()``, which frees ONLY the pruned branch's private
  blocks (shared prompt/ancestor blocks stay until the last sibling
  drops them) and seals a complete ``finish_reason="pruned"`` result —
  a pruned handle is never left wedged.

These classes are thin views: all state lives in the engine's slots
and the arena's refcounts.  Holding a handle after the engine retires
the branch is always safe — ``result()`` works forever.
"""

from __future__ import annotations

__all__ = ["BranchHandle", "ForkHandle"]


class BranchHandle:
    """One decoding branch: a :class:`RequestHandle` plus the fork
    verbs.  Delegates ``done``/``result`` to the wrapped handle;
    ``fork``/``prune`` act on the engine while the branch is live."""

    def __init__(self, engine, handle, branch=0):
        self._engine = engine
        self._handle = handle
        self.branch = int(branch)

    @property
    def request(self):
        return self._handle.request

    @property
    def request_id(self):
        return self._handle.request.request_id

    def done(self):
        return self._handle.done()

    def result(self):
        return self._handle.result()

    def fork(self, *, seed=None, max_new_tokens=None):
        """Split this LIVE branch into two: the original keeps its
        sampling chain, the returned sibling re-keys (``fold_in`` of
        the parent key by the new branch index, or a fresh chain from
        ``seed``) and optionally gets its own remaining-token budget.
        Every block decoded so far is shared copy-on-write."""
        return self._engine.fork(self.request_id, seed=seed,
                                 max_new_tokens=max_new_tokens)

    def prune(self):
        """Cut this branch: frees its private (unshared, non-trash)
        blocks immediately and seals a ``finish_reason="pruned"``
        result carrying everything emitted so far.  Sibling branches
        are untouched.  No-op if the branch already finished."""
        if self._handle.done():
            return
        self._engine.prune(self.request_id)

    def __repr__(self):
        state = "done" if self._handle.done() else "live"
        return (f"BranchHandle({self.request_id!r}, "
                f"branch={self.branch}, {state})")


class ForkHandle:
    """The ``n > 1`` submission surface: one prompt, n branches.

    ``branches`` lists a :class:`BranchHandle` per branch (branch 0 is
    the parent — the exact stream ``n=1`` would have produced).  The
    engine forks siblings synchronously during the parent's admission
    pass, so once the parent is admitted the list is complete; before
    admission it holds just the queued parent (whose rejection, e.g. a
    passed deadline, is then the whole group's rejection).
    """

    def __init__(self, engine, parent_handle):
        self._engine = engine
        self._parent = parent_handle
        self._parent_branch = BranchHandle(engine, parent_handle, 0)

    @property
    def request(self):
        return self._parent.request

    @property
    def request_id(self):
        return self._parent.request.request_id

    @property
    def branches(self):
        """Parent branch plus every sibling forked off it so far."""
        kids = getattr(self._parent, "_fork_children", None) or []
        return [self._parent_branch] + [
            BranchHandle(self._engine, h, i + 1)
            for i, h in enumerate(kids)]

    def done(self):
        bs = self.branches
        return all(b.done() for b in bs) and (
            len(bs) >= self.request.n or self._parent._error is not None)

    def results(self):
        """Every branch's terminal result, branch order (pruned
        included).  Raises the group rejection if a branch was
        rejected."""
        return [b.result() for b in self.branches]

    def ranked(self):
        """Completed (non-pruned, non-rejected) results, best first:
        sorted by cumulative chosen-token logprob ``score``, branch
        index breaking ties deterministically."""
        out = []
        for b in self.branches:
            if not b.done() or b._handle._error is not None:
                continue
            r = b._handle._result
            if r is not None and r.finish_reason != "pruned":
                out.append(r)
        return sorted(out, key=lambda r: (-(r.score or 0.0), r.branch))

    def best(self):
        """Highest-scoring completed result (best-of-n's answer)."""
        ranked = self.ranked()
        if not ranked:
            raise RuntimeError(
                f"{self.request_id}: no completed branch to rank — "
                "drive the engine to completion first (or every "
                "branch was pruned/rejected)")
        return ranked[0]

    def __repr__(self):
        bs = self.branches
        return (f"ForkHandle({self.request_id!r}, n={self.request.n}, "
                f"branches={len(bs)}, "
                f"done={sum(1 for b in bs if b.done())})")
