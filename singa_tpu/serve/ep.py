"""Expert-parallel MoE serving: one engine's EXPERTS sharded across an
``ep`` mesh axis, dense layers keeping the Megatron TP layout on an
orthogonal ``tp`` axis (the EP-serve round; GShard-style
capacity-bounded expert dispatch composed with the Megatron-LM decode
layout serve/tp.py already runs — ROADMAP item 4's first half).

The serve stack could shard a DENSE model (serve/tp.py) but refused
MoE outright — the expert axis is not the tensor-parallel axis, so a
trained GPT-MoE had no serve story.  This module is the second
executor behind the pluggable ``engine._x`` seam:

* **mesh** — a 2-D ``(ep, tp)`` mesh
  (``parallel.sharding.create_ep_mesh``): the stacked ``moe_*`` expert
  weights shard their leading expert axis over ``ep``
  (``tensor_parallel.decode_param_specs(ep_axis=)``), the dense
  attention/embedding weights ride the Megatron column/row layout over
  ``tp`` exactly as serve/tp.py lays them (replicated over ``ep``),
  and every KV arena keeps the TP head-axis sharding (replicated over
  ``ep`` — experts hold no KV);
* **routing** — per-token top-k gating with CAPACITY-BOUNDED dispatch
  inside the jitted pool-step twins (decode, spec chunk, prefill, warm
  chunk): ``gpt2_decode._moe_ffn_ep`` reuses ``parallel/moe.py``'s
  ``_top1_dispatch``/``_top2_dispatch`` one-hots (the training layer's
  routing math, verbatim), each rank computes only its RESIDENT
  experts' contributions, and ONE ``lax.psum`` over ``ep`` per MoE
  layer sums each token's top-k expert outputs — the degenerate
  all-to-all for replicated decode activations (every rank already
  holds every token, so only the combine half communicates);
* **capacity / drops** — ``EPConfig(capacity_factor=None)`` (default)
  sets capacity to the dispatch's token count: nothing drops, routing
  is per-token independent, and EP streams are pinned token-identical
  to the single-device MoE engine (greedy + seeded, GQA, int8, paged
  preempt-resume — tests/test_ep_serve.py; the ep psum is the one
  arithmetic difference, the same near-tie caveat as the TP psum).  A
  FINITE factor is the GShard capacity mode: expert buffers are
  (E/ep, C, D)-bounded and over-capacity assignments DROP — the
  combine weight goes to zero and the transformer block's RESIDUAL
  path carries the token (renormalized to the surviving expert when
  one of a top-2 pair drops; never a silently zeroed hidden state) —
  deterministic per workload, counted, and refused next to the prefix
  cache (capacity couples tokens within a dispatch group, so chunked
  prefill would stop being canonical with full prefill — the
  warm==cold byte-identity contract cannot survive it);
* **observability** — the dispatch twins RETURN their routing load:
  every EP twin carries two extra replicated outputs (tokens routed
  per expert, assignments dropped — ``parallel.moe.dispatch_load``,
  collected at trace time by ``gpt2_decode._ep_collecting``), and the
  executor feeds ``serve.ep.expert_tokens{engine=,expert=}`` +
  ``serve.ep.dropped_tokens{engine=}`` counters, the
  ``EngineStats.snapshot()["ep"]`` section (with a max/mean
  ``load_imbalance`` — an imbalanced router is the MoE why_slow), and
  ``health_report()["serve"]["ep"]``.

Twins are cached MODULE-WIDE keyed like TP's — supervisor rebuild or
an identical fleet replica is a compile-cache hit (``recompiles: 0``,
counted by ``bench_serve._serve_jit_cache_size``).  Every sharded
dispatch checks the ``serve.ep_dispatch`` fault site: an injected
fault is a raising sharded step — the engine fails TYPED and the
supervisor rebuilds (bench_chaos.py ``chaos_ep`` gates zero
wedged/lost/leaked).

Scope: MoE models (``cfg.moe_every``); ``ep`` must divide
``moe_experts`` and the orthogonal ``tp`` must divide
``n_head``/``n_kv_head``/``n_inner``.  Dense models take ``tp=``
(serve/tp.py); a model carrying a training ``ShardingPlan`` owns its
layout already — both rejected typed at construction, BEFORE any
registry registration.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observe import trace as _trace
from ..observe.registry import registry as _default_registry
from ..parallel.sharding import EP as EP_AXIS
from ..parallel.sharding import TP as TP_AXIS
from ..parallel.sharding import create_ep_mesh
from ..parallel.tensor_parallel import (decode_cache_spec,
                                        decode_param_specs)
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["EPConfig", "EPExecutor", "fleet_ep_configs"]

import jax.numpy as jnp

#: replicated spec over the 2-D (ep, tp) mesh
_R = P()
#: KV leaves: head axis (axis 2) over tp, replicated over ep —
#: experts hold no KV, so the cache layout is exactly serve/tp.py's
_CS = decode_cache_spec(TP_AXIS)

# module-wide twin cache, keyed like tp.py's: (base, extra statics,
# executor key) -> jitted sharded executable
_TWINS = {}


def _twin_cache_size():
    """Compiled-signature count across every cached EP twin — counted
    by ``bench_serve._serve_jit_cache_size`` next to the jit caches so
    the sharded dispatch path cannot recompile unnoticed."""
    total = 0
    for f in _TWINS.values():
        try:
            total += f._cache_size()
        except Exception:
            return None
    return total


@dataclass(frozen=True)
class EPConfig:
    """Knobs for the expert-parallel serve backend (hand to
    ``model.serve(ep=...)`` — a bare int is shorthand for
    ``EPConfig(ep=k)``; the supervisor/fleet forward it verbatim so a
    rebuilt replica lands on the SAME device group and reuses the same
    compiled twins).

    ``ep``: expert-shard count (must divide ``cfg.moe_experts``).
    ``tp``: orthogonal tensor-parallel width for the DENSE layers
    (Megatron column/row, one psum per attention out-proj and MLP fc2
    — serve/tp.py's layout; must divide n_head/n_kv_head/n_inner; 1 =
    dense layers replicated).  The mesh is ``ep x tp`` devices.
    ``devices``: explicit device tuple (default: the first ``ep*tp``
    of ``jax.devices()``) — the fleet hands each EP replica a disjoint
    slice (:func:`fleet_ep_configs`).
    ``capacity_factor``: GShard expert capacity per dispatch group —
    ``C = ceil(top_k * tokens / E * capacity_factor)``.  ``None``
    (default) means capacity == tokens: drop-free, per-token
    independent routing, exact single-device-oracle parity — the serve
    default, because serving wants parity and capacity is a
    buffer-size knob.  A finite factor bounds the (E/ep, C, D) expert
    buffers and DROPS over-capacity assignments through the residual
    path (deterministic, counted in ``serve.ep.dropped_tokens``);
    it is refused next to a prefix cache (chunk canonicality —
    docs/SERVING.md 'Expert-parallel and pipeline serving')."""

    ep: int = 2
    tp: int = 1
    devices: tuple | None = None
    capacity_factor: float | None = None

    def __post_init__(self):
        if self.ep < 1:
            raise ValueError(f"ep must be >= 1, got {self.ep}")
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.devices is not None \
                and len(self.devices) < self.ep * self.tp:
            raise ValueError(
                f"EPConfig(ep={self.ep}, tp={self.tp}) with only "
                f"{len(self.devices)} explicit devices")
        if self.capacity_factor is not None \
                and self.capacity_factor <= 0:
            raise ValueError(
                f"capacity_factor must be > 0 (or None for drop-free "
                f"full capacity), got {self.capacity_factor}")


def as_ep_config(ep):
    """Normalize the ``ep=`` knob (bare int expert-shard count, kwargs
    dict, or an EPConfig) — the ONE coercion the engine and the fleet
    both apply."""
    if isinstance(ep, EPConfig):
        return ep
    if isinstance(ep, int) and not isinstance(ep, bool):
        return EPConfig(ep=ep)
    if isinstance(ep, dict):
        return EPConfig(**ep)
    raise ValueError(
        f"ep must be an int expert-shard count, an EPConfig, or a "
        f"kwargs dict, got {type(ep)}")


def check_ep(config, cfg, model_plan=None, prefix_cache=None):
    """The full EP composition/validity matrix, TYPED — callable
    BEFORE any registry/executor/arena state exists (the engine runs
    it first so a refused construction leaks no metrics; the executor
    re-runs it defensively before registering anything)."""
    if model_plan is not None:
        raise ValueError(
            "ep= on a plan-sharded model: the training ShardingPlan "
            "already owns the weight layout; build the serve model "
            "without a plan and let the EP backend place the decode "
            "weights")
    if getattr(cfg, "moe_every", None) is None:
        raise ValueError(
            f"ep={config.ep} on a dense model (no MoE blocks): there "
            f"is no expert axis to shard — serve dense/GQA models "
            f"with tp= (serve/tp.py)")
    n_exp = int(cfg.moe_experts)
    if n_exp % config.ep != 0:
        raise ValueError(
            f"ep={config.ep} does not divide moe_experts ({n_exp}): "
            f"every shard must own a whole number of experts")
    for what, n in (("n_head", cfg.n_head),
                    ("n_kv_head (H_kv)", cfg.n_kv_head),
                    ("n_inner", cfg.n_inner)):
        if n % config.tp != 0:
            raise ValueError(
                f"EPConfig(tp={config.tp}) does not divide {what} "
                f"({n}): the dense layers' Megatron layout needs a "
                f"whole head/column count per tp shard")
    if config.capacity_factor is not None and prefix_cache is not None \
            and prefix_cache is not False:
        raise ValueError(
            "ep with a finite capacity_factor AND a prefix_cache: "
            "capacity-bounded routing couples tokens within a "
            "dispatch group, so chunked prefill K/V is no longer "
            "canonical with full prefill and the cache's warm==cold "
            "byte-identity contract cannot hold; serve with "
            "capacity_factor=None (drop-free) or drop the cache "
            "(docs/SERVING.md 'Expert-parallel and pipeline serving')")


def fleet_ep_configs(ep, replicas, devices=None):
    """Disjoint per-replica :class:`EPConfig`\\ s: replica ``i`` owns
    the ``ep*tp``-wide device group ``[i*g, (i+1)*g)`` — expert (and
    dense-tensor) parallelism inside each replica, data parallelism
    across them.  Raises when the groups exceed the mesh."""
    ep = as_ep_config(ep)
    if ep.ep * ep.tp == 1:
        return [ep] * replicas
    devs = (list(ep.devices) if ep.devices is not None
            else list(jax.devices()))
    g = ep.ep * ep.tp
    need = g * replicas
    if need > len(devs):
        raise ValueError(
            f"(ep x tp) x replicas ({ep.ep} x {ep.tp} x {replicas} = "
            f"{need}) exceeds the {len(devs)}-device mesh; shrink the "
            f"fleet or the group, or provision a larger virtual mesh "
            f"via XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{need}")
    return [EPConfig(ep=ep.ep, tp=ep.tp,
                     capacity_factor=ep.capacity_factor,
                     devices=tuple(devs[i * g:(i + 1) * g]))
            for i in range(replicas)]


def _fold_ep_stats(rec, n_expert, live_r=None):
    """Sum one row/body trace's collected (counts, dropped) pairs —
    one pair per MoE layer application.  ``live_r``: the row's live
    flag — dead decode lanes run clamped garbage through the router
    and must not pollute the load counters."""
    if rec:
        cnt = sum(c for c, _ in rec)
        drp = sum(d for _, d in rec)
    else:
        cnt = jnp.zeros((n_expert,), jnp.int32)
        drp = jnp.int32(0)
    if live_r is not None:
        cnt = jnp.where(live_r, cnt, 0)
        drp = jnp.where(live_r, drp, 0)
    return cnt, drp


class EPExecutor:
    """The engine's expert-parallel executor: owns the ``(ep, tp)``
    mesh, the expert + Megatron weight placement, the stats-carrying
    sharded-twin dispatch, and the ``serve.ep.*`` metrics.  Built by
    ``InferenceEngine`` when ``ep=`` is set; the engine routes every
    target-side dispatch through the same surface ``_LocalExec`` /
    ``TPExecutor`` expose."""

    def __init__(self, config, cfg, statics, quant, model_plan=None,
                 engine_label="0", reg=None, prefix_cache=None):
        # the FULL validity matrix runs before anything registers —
        # a refused construction must leak no metrics (the PR-12
        # leaked-gauge hazard)
        check_ep(config, cfg, model_plan=model_plan,
                 prefix_cache=prefix_cache)
        self.mesh = create_ep_mesh(config.ep, config.tp,
                                   devices=config.devices)
        self.config = config
        self.ep = int(config.ep)
        self.tp = int(config.tp)
        self.n_expert = int(cfg.moe_experts)
        self.n_layer = int(cfg.n_layer)
        self._cap = (None if config.capacity_factor is None
                     else float(config.capacity_factor))
        #: the static triple gpt2_decode._mlp routes the MoE FFN on
        self._ep3 = (EP_AXIS, self.ep, self._cap)
        self._statics = dict(statics)
        self._quant = bool(quant)
        self._spec = None
        self._chunk = None
        self._window = None
        self._pspec = None
        self._cache_sh = NamedSharding(self.mesh, _CS)
        self._repl_sh = NamedSharding(self.mesh, _R)
        self._kv_bytes = 0
        self._log = get_channel("serve")
        self._key = (self.ep, self.tp, self._cap,
                     tuple(int(d.id) for d in self.mesh.devices.flat),
                     tuple(sorted(self._statics.items())),
                     self._quant)
        reg = reg if reg is not None else _default_registry()
        lbl = dict(engine=engine_label)
        self._g_shards = reg.gauge(
            "serve.ep.shards",
            help="expert-parallel shard count (experts per shard = "
                 "moe_experts / ep)", **lbl)
        self._g_tp = reg.gauge(
            "serve.ep.dense_tp",
            help="orthogonal tensor-parallel width of the dense "
                 "layers inside the (ep, tp) mesh", **lbl)
        self._g_kv = reg.gauge(
            "serve.ep.kv_bytes_per_shard",
            help="persistent KV-cache bytes each tp shard holds "
                 "(experts hold no KV — the arena shards over tp "
                 "only, replicated over ep)", **lbl)
        self._c_dispatch = reg.counter(
            "serve.ep.sharded_dispatches",
            help="sharded-twin executions under the (ep, tp) mesh",
            **lbl)
        self._c_dropped = reg.counter(
            "serve.ep.dropped_tokens",
            help="top-k expert assignments capacity bounded away "
                 "(the token rides the residual path; only a finite "
                 "EPConfig.capacity_factor can drop)", **lbl)
        self._c_expert = [
            reg.counter(
                "serve.ep.expert_tokens",
                help="tokens routed to (and kept by) each expert — "
                     "the router load-balance signal; an imbalanced "
                     "router is the MoE why_slow",
                expert=str(e), **lbl)
            for e in range(self.n_expert)]
        self._g_shards.set(self.ep)
        self._g_tp.set(self.tp)
        self._g_kv.set(0)
        self._registered = [self._g_shards, self._g_tp, self._g_kv,
                            self._c_dispatch, self._c_dropped,
                            *self._c_expert]
        self._registry = reg
        self.expert_tokens = np.zeros(self.n_expert, np.int64)
        self.dropped_tokens = 0
        self._pending_stats = []   # lazy chunk-path (cnt, drp) queue
        self._log.info(
            "ep executor up: %d expert shards x %d tp over %s "
            "(capacity_factor=%s)", self.ep, self.tp,
            [str(d) for d in self.mesh.devices.flat], self._cap)

    # -- placement --------------------------------------------------------
    def place_params(self, params):
        """Lay the decode weights out over the 2-D mesh: stacked
        ``moe_*`` expert weights on their leading axis over ``ep``,
        dense attention/MLP Megatron-style over ``tp``, everything
        else replicated (``decode_param_specs(ep_axis=)``)."""
        self._pspec = decode_param_specs(params, axis=TP_AXIS,
                                         ep_axis=EP_AXIS)
        self._key = self._key + (jax.tree.structure(params),)
        return jax.tree.map(
            lambda a, s: jax.device_put(
                a, NamedSharding(self.mesh, s)), params, self._pspec)

    def place_cache(self, tree):
        placed = jax.tree.map(
            lambda a: jax.device_put(a, self._cache_sh), tree)
        self._kv_bytes += sum(a.nbytes
                              for a in jax.tree.leaves(tree)) // self.tp
        self._g_kv.set(self._kv_bytes)
        return placed

    def place_replicated(self, tree):
        return jax.tree.map(
            lambda a: jax.device_put(a, self._repl_sh), tree)

    # -- late statics -----------------------------------------------------
    def set_spec(self, spec_k, d_statics):
        self._spec = (int(spec_k), tuple(d_statics))

    def set_chunk(self, chunk_statics):
        self._chunk = dict(chunk_statics)

    def set_window(self, window):
        self._window = None if window is None else int(window)

    # -- twin dispatch ----------------------------------------------------
    def _twin(self, base, extra, make, donate=()):
        key = (base, extra, self._key)
        fn = _TWINS.get(key)
        if fn is None:
            fn = jax.jit(
                jax.shard_map(make(), mesh=self.mesh,
                              in_specs=self._in_specs(base),
                              out_specs=self._out_specs(base),
                              check_vma=False),
                donate_argnums=donate)
            _TWINS[key] = fn
        return fn

    def _dispatch(self, fn, *args):
        """Run a twin: ``serve.ep_dispatch`` fault site, dispatch
        counter, compile-visibility instant — and for the compute
        twins, strip the two trailing stats outputs into the
        expert-load counters (one tiny host fetch per dispatch; the
        engine syncs the same dispatch's tokens right after, so this
        adds no extra wait)."""
        if _faults._armed:
            _faults.check("serve.ep_dispatch")
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        out = fn(*args)
        if before is not None and fn._cache_size() != before:
            _trace.event("serve/compile", cat="serve", fn="serve.ep",
                         shards=self.ep)
        self._c_dispatch.inc()
        return out

    def _dispatch_stats(self, fn, *args):
        out = self._dispatch(fn, *args)
        *std, cnt, drp = out
        self._flush_stats()
        self._fold_stats(cnt, drp)
        return tuple(std)

    def _dispatch_stats_lazy(self, fn, *args):
        """Like :meth:`_dispatch_stats` but WITHOUT the host fetch:
        the chunk-row path issues many dispatches back to back (warm
        prefill, the chunked-prefill budget) and deliberately stays
        async — a per-chunk stats sync would serialize exactly the
        TTFT pipeline chunking exists for.  The device arrays queue
        and fold at the next synchronous dispatch (every decode step)
        or at :meth:`snapshot` — bounded by the chunks of one
        admission, never unbounded."""
        out = self._dispatch(fn, *args)
        *std, cnt, drp = out
        self._pending_stats.append((cnt, drp))
        return tuple(std)

    def _fold_stats(self, cnt, drp):
        cnt = np.asarray(cnt)
        drp = int(np.asarray(drp))
        self.expert_tokens += cnt
        for e, c in enumerate(cnt):
            if c:
                self._c_expert[e].inc(int(c))
        if drp:
            self.dropped_tokens += drp
            self._c_dropped.inc(drp)

    def _flush_stats(self):
        if self._pending_stats:
            pend, self._pending_stats = self._pending_stats, []
            for cnt, drp in pend:
                self._fold_stats(cnt, drp)

    def _in_specs(self, base):
        ps = self._pspec
        return {
            "pool_decode": (ps, _CS, _CS, _R, _R, _R, _R, _R, _R),
            "pool_spec": (ps, _R, _CS, _CS, _R, _R, _R, _R, _R, _R,
                          _R, _R),
            "prefill_one": (ps, _R, _R, _R, _R, _R),
            "prefill_batch": (ps, _R, _R, _R, _R, _R),
            "chunk_row": (ps, _R, _CS, _CS, _R),
            "paged_decode": (ps, _CS, _CS, _R, _R, _R, _R, _R, _R,
                             _R),
            "paged_spec": (ps, _R, _CS, _CS, _R, _R, _R, _R, _R, _R,
                           _R, _R, _R),
            "write_slot": (_CS, _CS, _CS, _CS, _R),
            "read_slot": (_CS, _CS, _R),
            "pool_to_row": (_CS, _CS, _R, _R),
            "row_to_pool": (_CS, _CS, _CS, _CS, _R),
            "rows_to_pool": (_CS, _CS, _CS, _CS, _R, _R),
        }[base]

    def _out_specs(self, base):
        # compute twins append two REPLICATED stats outputs (routing
        # is computed from replicated activations, identically on
        # every rank)
        return {
            "pool_decode": (_R, _CS, _CS, _R, _R, _R),
            "pool_spec": (_R, _R, _CS, _CS, _R, _R, _R, _R, _R),
            "prefill_one": (_R, _R, _CS, _CS, _R, _R),
            "prefill_batch": (_R, _R, _CS, _CS, _R, _R),
            "chunk_row": (_R, _CS, _CS, _R, _R),
            "paged_decode": (_R, _CS, _CS, _R, _R, _R),
            "paged_spec": (_R, _R, _CS, _CS, _R, _R, _R, _R, _R),
            "write_slot": (_CS, _CS),
            "read_slot": (_CS, _CS),
            "pool_to_row": (_CS, _CS),
            "row_to_pool": (_CS, _CS),
            "rows_to_pool": (_CS, _CS),
        }[base]

    # -- twin bodies ------------------------------------------------------
    # The engine's pool steps vmap a per-row function; the EP stats
    # collector must be consumed INSIDE the vmapped row (its tracers
    # belong to the row's trace), so the small vmap wrappers are
    # restated here with the shared row math untouched — the per-slot
    # ops are engine._decode_row/_spec_row/_decode_row_paged/... with
    # the ep triple threaded, one definition, no drift.

    def _mk_pool_decode(self):
        from .engine import _decode_row

        st, ep3, tpw = self._statics, self._ep3, self.tp
        from ..models import gpt2_decode as G
        ne = self.n_expert

        def body(params, kc, vc, toks, pos, live, keys, temps, top_p):
            def row(kc_r, vc_r, tok, pos_r, live_r, key, temp):
                with G._ep_collecting() as rec:
                    nxt, kc2, vc2, k2 = _decode_row(
                        params, kc_r, vc_r, tok, pos_r, live_r, key,
                        temp, top_p, **st, tp_axis=TP_AXIS,
                        tp_world=tpw, ep=ep3)
                cnt, drp = _fold_ep_stats(rec, ne, live_r)
                return nxt, kc2, vc2, k2, cnt, drp

            nxt, kc, vc, keys2, cnt, drp = jax.vmap(
                row, in_axes=(1, 1, 0, 0, 0, 0, 0),
                out_axes=(0, 1, 1, 0, 0, 0))(kc, vc, toks, pos, live,
                                             keys, temps)
            return nxt, kc, vc, keys2, cnt.sum(0), drp.sum()

        return body

    def _mk_pool_spec(self):
        from .engine import _spec_row

        from ..models import gpt2_decode as G

        st, ep3, tpw = self._statics, self._ep3, self.tp
        ne = self.n_expert
        spec_k, (dn, de, dm) = self._spec

        def body(t_params, d_params, kc, vc, dkc, dvc, toks, pos,
                 live, keys, temps, top_p):
            def row(kc_r, vc_r, dkc_r, dvc_r, tok, pos_r, live_r, key,
                    temp):
                with G._ep_collecting() as rec:
                    out, a_draft, kc2, vc2, dkc2, dvc2, k2 = _spec_row(
                        t_params, d_params, kc_r, vc_r, dkc_r, dvc_r,
                        tok, pos_r, live_r, key, temp, top_p, spec_k,
                        st["n_head"], st["eps"], st["moe_top_k"], dn,
                        de, dm, st["top_k"], st["use_top_p"],
                        tp_axis=TP_AXIS, tp_world=tpw, ep=ep3)
                cnt, drp = _fold_ep_stats(rec, ne, live_r)
                return (out, a_draft, kc2, vc2, dkc2, dvc2, k2, cnt,
                        drp)

            (out, a_draft, kc, vc, dkc, dvc, keys2, cnt,
             drp) = jax.vmap(
                row, in_axes=(1, 1, 1, 1, 0, 0, 0, 0, 0),
                out_axes=(0, 0, 1, 1, 1, 1, 0, 0, 0))(
                kc, vc, dkc, dvc, toks, pos, live, keys, temps)
            return (out, a_draft, kc, vc, dkc, dvc, keys2,
                    cnt.sum(0), drp.sum())

        return body

    def _mk_paged_decode(self, block, kernel):
        from .engine import _decode_row, _decode_row_paged
        from .paged import _gather_leaf

        from ..models import gpt2_decode as G

        st, ep3, tpw = self._statics, self._ep3, self.tp
        ne = self.n_expert
        window = self._window

        def body(params, pool_k, pool_v, tables, toks, pos, live,
                 keys, temps, top_p):
            trash = jax.tree.leaves(pool_k)[0].shape[1] - 1
            p_all = jnp.where(live, pos, 0)
            n_blk = jnp.max((p_all + block - 1) // block)
            blk_lo = None
            if kernel == "block" and window is not None:
                lo = jnp.maximum(0, (p_all - window + 1) // block)
                blk_lo = jnp.min(jnp.where(live, lo, n_blk))

            def row(tbl, tok, pos_r, live_r, key, temp):
                with G._ep_collecting() as rec:
                    if kernel == "block":
                        nxt, kb, vb, k2 = _decode_row_paged(
                            params, pool_k, pool_v, tbl, tok, pos_r,
                            live_r, key, temp, top_p, n_blk, block,
                            trash, **st, window=window, blk_lo=blk_lo,
                            tp_axis=TP_AXIS, tp_world=tpw, ep=ep3)
                    else:
                        kc_r = jax.tree.map(
                            lambda p: _gather_leaf(p, tbl), pool_k)
                        vc_r = jax.tree.map(
                            lambda p: _gather_leaf(p, tbl), pool_v)
                        nxt, kc2, vc2, k2 = _decode_row(
                            params, kc_r, vc_r, tok, pos_r, live_r,
                            key, temp, top_p, **st, tp_axis=TP_AXIS,
                            tp_world=tpw, ep=ep3)
                        from .paged import _slice_block
                        p_c0 = jnp.where(live_r, pos_r, 0)
                        off = (p_c0 // block) * block
                        kb = jax.tree.map(
                            lambda a: _slice_block(a, off, block), kc2)
                        vb = jax.tree.map(
                            lambda a: _slice_block(a, off, block), vc2)
                cnt, drp = _fold_ep_stats(rec, ne, live_r)
                p_c = jnp.where(live_r, pos_r, 0)
                dst = jnp.where(live_r, tbl[p_c // block], trash)
                return nxt, kb, vb, dst, k2, cnt, drp

            nxt, kb, vb, dst, keys2, cnt, drp = jax.vmap(
                row, in_axes=(0, 0, 0, 0, 0, 0),
                out_axes=(0, 1, 1, 0, 0, 0, 0))(tables, toks, pos,
                                                live, keys, temps)
            pool_k = jax.tree.map(lambda p, b: p.at[:, dst].set(b),
                                  pool_k, kb)
            pool_v = jax.tree.map(lambda p, b: p.at[:, dst].set(b),
                                  pool_v, vb)
            return nxt, pool_k, pool_v, keys2, cnt.sum(0), drp.sum()

        return body

    def _mk_paged_spec(self, block, kernel):
        from .engine import _spec_row, _spec_row_paged
        from .paged import _gather_leaf, _slice_block

        from ..models import gpt2_decode as G

        st, ep3, tpw = self._statics, self._ep3, self.tp
        ne = self.n_expert
        window = self._window
        spec_k, (dn, de, dm) = self._spec

        def body(t_params, d_params, pool_k, pool_v, dkc, dvc, tables,
                 toks, pos, live, keys, temps, top_p):
            trash = jax.tree.leaves(pool_k)[0].shape[1] - 1
            p_all = jnp.where(live, pos, 0)
            n_blk = jnp.max((p_all + block - 1) // block)
            blk_lo = None
            if kernel == "block" and window is not None:
                lo = jnp.maximum(0, (p_all - window + 1) // block)
                blk_lo = jnp.min(jnp.where(live, lo, n_blk))

            def row(dkc_r, dvc_r, tbl, tok, pos_r, live_r, key, temp):
                with G._ep_collecting() as rec:
                    if kernel == "block":
                        (out, a_draft, kdbl, vdbl, dkc2, dvc2,
                         k2) = _spec_row_paged(
                            t_params, d_params, pool_k, pool_v, dkc_r,
                            dvc_r, tbl, tok, pos_r, live_r, key, temp,
                            top_p, n_blk, spec_k, block, trash,
                            st["n_head"], st["eps"], st["moe_top_k"],
                            dn, de, dm, st["top_k"], st["use_top_p"],
                            window=window, blk_lo=blk_lo,
                            tp_axis=TP_AXIS, tp_world=tpw, ep=ep3)
                        kb0 = jax.tree.map(lambda a: a[:, :, :block],
                                           kdbl)
                        vb0 = jax.tree.map(lambda a: a[:, :, :block],
                                           vdbl)
                        kb1 = jax.tree.map(lambda a: a[:, :, block:],
                                           kdbl)
                        vb1 = jax.tree.map(lambda a: a[:, :, block:],
                                           vdbl)
                    else:
                        kc_r = jax.tree.map(
                            lambda p: _gather_leaf(p, tbl), pool_k)
                        vc_r = jax.tree.map(
                            lambda p: _gather_leaf(p, tbl), pool_v)
                        (out, a_draft, kc2, vc2, dkc2, dvc2,
                         k2) = _spec_row(
                            t_params, d_params, kc_r, vc_r, dkc_r,
                            dvc_r, tok, pos_r, live_r, key, temp,
                            top_p, spec_k, st["n_head"], st["eps"],
                            st["moe_top_k"], dn, de, dm, st["top_k"],
                            st["use_top_p"], tp_axis=TP_AXIS,
                            tp_world=tpw, ep=ep3)
                        p_c0 = jnp.where(live_r, pos_r, 0)
                        o0 = (p_c0 // block) * block
                        o1 = ((p_c0 + spec_k - 1) // block) * block
                        kb0 = jax.tree.map(
                            lambda a: _slice_block(a, o0, block), kc2)
                        vb0 = jax.tree.map(
                            lambda a: _slice_block(a, o0, block), vc2)
                        kb1 = jax.tree.map(
                            lambda a: _slice_block(a, o1, block), kc2)
                        vb1 = jax.tree.map(
                            lambda a: _slice_block(a, o1, block), vc2)
                cnt, drp = _fold_ep_stats(rec, ne, live_r)
                p_c = jnp.where(live_r, pos_r, 0)
                b0 = p_c // block
                b1 = (p_c + spec_k - 1) // block
                dst0 = jnp.where(live_r, tbl[b0], trash)
                dst1 = jnp.where(live_r & (b1 > b0), tbl[b1], trash)
                return (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1,
                        dkc2, dvc2, k2, cnt, drp)

            (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1, dkc, dvc,
             keys2, cnt, drp) = jax.vmap(
                row, in_axes=(1, 1, 0, 0, 0, 0, 0, 0),
                out_axes=(0, 0, 1, 1, 0, 1, 1, 0, 1, 1, 0, 0, 0))(
                dkc, dvc, tables, toks, pos, live, keys, temps)
            pool_k = jax.tree.map(lambda p, b: p.at[:, dst0].set(b),
                                  pool_k, kb0)
            pool_v = jax.tree.map(lambda p, b: p.at[:, dst0].set(b),
                                  pool_v, vb0)
            pool_k = jax.tree.map(lambda p, b: p.at[:, dst1].set(b),
                                  pool_k, kb1)
            pool_v = jax.tree.map(lambda p, b: p.at[:, dst1].set(b),
                                  pool_v, vb1)
            return (out, a_draft, pool_k, pool_v, dkc, dvc, keys2,
                    cnt.sum(0), drp.sum())

        return body

    def _mk_prefill_one(self):
        from .engine import _prefill_one

        from ..models import gpt2_decode as G

        st, ep3, tpw = self._statics, self._ep3, self.tp
        ne = self.n_expert
        quant, window = self._quant, self._window

        def body(params, ids, prompt_len, key, temp, top_p):
            with G._ep_collecting() as rec:
                out = _prefill_one.__wrapped__(
                    params, ids, prompt_len, key, temp, top_p, **st,
                    quant=quant, window=window, tp_axis=TP_AXIS,
                    tp_world=tpw, ep=ep3)
            cnt, drp = _fold_ep_stats(rec, ne)
            return (*out, cnt, drp)

        return body

    def _mk_prefill_batch(self):
        from .engine import _prefill_one

        from ..models import gpt2_decode as G

        st, ep3, tpw = self._statics, self._ep3, self.tp
        ne = self.n_expert
        quant, window = self._quant, self._window

        def body(params, ids, plens, seeds, temps, top_p):
            def row(ids_r, plen, seed, temp):
                key0 = jax.random.split(jax.random.PRNGKey(seed), 1)[0]
                with G._ep_collecting() as rec:
                    out = _prefill_one.__wrapped__(
                        params, ids_r[None], plen, key0, temp, top_p,
                        **st, quant=quant, window=window,
                        tp_axis=TP_AXIS, tp_world=tpw, ep=ep3)
                cnt, drp = _fold_ep_stats(rec, ne)
                return (*out, cnt, drp)

            tok0, keys, kc, vc, cnt, drp = jax.vmap(
                row, in_axes=(0, 0, 0, 0),
                out_axes=(0, 0, 1, 1, 0, 0))(ids, plens, seeds, temps)
            sq = lambda a: a[:, :, 0]
            return (tok0, keys, jax.tree.map(sq, kc),
                    jax.tree.map(sq, vc), cnt.sum(0), drp.sum())

        return body

    def _mk_chunk_row(self):
        from .engine import _chunk_row

        from ..models import gpt2_decode as G

        ck = dict(self._chunk)
        ep3, tpw = self._ep3, self.tp
        ne = self.n_expert

        def body(params, ids, kc_row, vc_row, off):
            with G._ep_collecting() as rec:
                out = _chunk_row.__wrapped__(
                    params, ids, kc_row, vc_row, off, **ck,
                    tp_axis=TP_AXIS, tp_world=tpw, ep=ep3)
            cnt, drp = _fold_ep_stats(rec, ne)
            return (*out, cnt, drp)

        return body

    # -- the executor surface (mirrors engine._LocalExec) -----------------
    def pool_decode_step(self, params, kc, vc, toks, pos, live, keys,
                         temps, top_p):
        fn = self._twin("pool_decode", (), self._mk_pool_decode,
                        donate=(1, 2))
        return self._dispatch_stats(fn, params, kc, vc, toks, pos,
                                    live, keys, temps, top_p)

    def pool_spec_step(self, t_params, d_params, kc, vc, dkc, dvc,
                       toks, pos, live, keys, temps, top_p):
        spec_k, d_st = self._spec
        fn = self._twin("pool_spec", (spec_k, d_st),
                        self._mk_pool_spec, donate=(2, 3, 4, 5))
        return self._dispatch_stats(fn, t_params, d_params, kc, vc,
                                    dkc, dvc, toks, pos, live, keys,
                                    temps, top_p)

    def paged_decode_step(self, params, pool_k, pool_v, tables, toks,
                          pos, live, keys, temps, top_p, block,
                          kernel="block"):
        fn = self._twin("paged_decode", (block, kernel, self._window),
                        lambda: self._mk_paged_decode(block, kernel),
                        donate=(1, 2))
        return self._dispatch_stats(fn, params, pool_k, pool_v,
                                    tables, toks, pos, live, keys,
                                    temps, top_p)

    def paged_spec_step(self, t_params, d_params, pool_k, pool_v, dkc,
                        dvc, tables, toks, pos, live, keys, temps,
                        top_p, block, kernel="block"):
        spec_k, d_st = self._spec
        fn = self._twin(
            "paged_spec", (block, kernel, spec_k, d_st, self._window),
            lambda: self._mk_paged_spec(block, kernel),
            donate=(2, 3, 4, 5))
        return self._dispatch_stats(fn, t_params, d_params, pool_k,
                                    pool_v, dkc, dvc, tables, toks,
                                    pos, live, keys, temps, top_p)

    def prefill_one(self, params, ids, prompt_len, key, temp, top_p):
        fn = self._twin("prefill_one", (self._window,),
                        self._mk_prefill_one)
        return self._dispatch_stats(fn, params, ids, prompt_len, key,
                                    temp, top_p)

    def prefill_batch(self, params, ids, plens, seeds, temps, top_p):
        fn = self._twin("prefill_batch", (self._window,),
                        self._mk_prefill_batch)
        return self._dispatch_stats(fn, params, ids, plens, seeds,
                                    temps, top_p)

    def chunk_row(self, params, ids, kc_row, vc_row, off):
        fn = self._twin("chunk_row",
                        tuple(sorted(self._chunk.items())),
                        self._mk_chunk_row, donate=(2, 3))
        return self._dispatch_stats_lazy(fn, params, ids, kc_row,
                                         vc_row, off)

    # -- cache copies (no MoE math — tp.py's bodies, EP's mesh) ----------
    def write_slot(self, kc, vc, kc_row, vc_row, slot):
        from .engine import _write_slot

        fn = self._twin("write_slot", (),
                        lambda: _write_slot.__wrapped__,
                        donate=(0, 1))
        return self._dispatch(fn, kc, vc, kc_row, vc_row, slot)

    def read_slot(self, kc, vc, slot):
        from .prefix import _read_slot

        fn = self._twin("read_slot", (),
                        lambda: _read_slot.__wrapped__)
        return self._dispatch(fn, kc, vc, slot)

    def pool_to_row(self, pool_k, pool_v, idx, n_used):
        from .tp import _pool_to_row_body

        fn = self._twin("pool_to_row", (),
                        lambda: _pool_to_row_body)
        return self._dispatch(fn, pool_k, pool_v, idx, n_used)

    def row_to_pool(self, pool_k, pool_v, kc_row, vc_row, idx):
        from .tp import _row_to_pool_body

        fn = self._twin("row_to_pool", (), lambda: _row_to_pool_body,
                        donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_row, vc_row, idx)

    def rows_to_pool(self, pool_k, pool_v, kc_rows, vc_rows, sel, idx):
        from .tp import _rows_to_pool_body

        fn = self._twin("rows_to_pool", (),
                        lambda: _rows_to_pool_body, donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_rows, vc_rows,
                              sel, idx)

    # -- lifecycle / reporting -------------------------------------------
    def unregister(self):
        """Release the registry entries (engine close()); the twin
        cache stays module-wide by design."""
        self._registry.remove(*self._registered)

    def snapshot(self) -> dict:
        self._flush_stats()
        toks = self.expert_tokens
        total = int(toks.sum())
        imb = (float(toks.max() / (toks.mean() or 1.0))
               if total else None)
        return {
            "shards": self.ep,
            "dense_tp": self.tp,
            "experts": self.n_expert,
            "experts_per_shard": self.n_expert // self.ep,
            "capacity_factor": self._cap,
            "devices": [str(d) for d in self.mesh.devices.flat],
            "kv_bytes_per_shard": self._kv_bytes,
            "sharded_dispatches": self._c_dispatch.value,
            "expert_tokens": [int(t) for t in toks],
            "dropped_tokens": self.dropped_tokens,
            # max/mean routed load — 1.0 is a perfectly balanced
            # router, E/top_k is total collapse onto one expert
            "load_imbalance": imb,
        }
