"""Versioned host-side KV block images — ONE wire format for
preemption swap and fleet KV shipping (the disaggregation round).

Two paths copy paged KV between device pools and host memory:

* **preemption swap** (serve/paged.py ``swap_out``/``swap_in``) — a
  preempted request's blocks round-trip through host RAM and resume
  byte-exactly;
* **KV shipping** (serve/fleet.py disaggregated serving) — a prefill
  specialist's canonical prompt blocks travel to a decode specialist's
  pool, seeding its radix prefix cache so the admission lands warm.

Before this module the swap image was a bare ``(kc_host, vc_host)``
numpy-pytree pair with no self-description: nothing stopped a drifted
producer (or a truncated transfer) from scattering garbage into a
live pool.  A :class:`KVImage` carries a VERSION, the block geometry,
the quantization flag, and a per-leaf dtype/shape header captured at
pack time; :meth:`KVImage.validate` re-derives the signature from the
arrays and cross-checks it against both the header (truncation /
mutation fails typed) and the consuming arena's geometry (a dense
image cannot scatter into an int8 pool, a block-size-16 image cannot
land in a block-size-32 pool).  Both swap and ship consume images
through the same checks, so the two paths cannot drift.

Leaf layout (the cache-row convention every fixed-shape copy in
serve/paged.py uses): dense pools are one ``(L, 1, H_kv, W, D)``
array per K/V; int8 pools are ``(values, scales)`` tuples whose
scales leaf drops the trailing ``D`` axis.  ``W`` is the image's lane
width — a FULL row for swap (the historical shape, one executable per
engine geometry) or the narrow ``n_data * block_size`` slice for
shipping (ship bytes track the prompt, not ``max_len``).

Since the multi-host round the image is also the WIRE format: every
image carries a crc32 ``checksum`` over its leaf bytes (captured at
pack time, re-derived in :meth:`KVImage.validate` — a bit-flip that
preserves shape and dtype fails typed, which the header check alone
cannot catch), and :meth:`KVImage.to_bytes` /
:meth:`KVImage.from_bytes` frame it for a socket: magic + version +
pickled metadata + raw leaf bytes + the checksum.  ``from_bytes``
rejects truncation (mid-stream EOF), corruption (checksum mismatch)
and version skew with :class:`KVImageError` BEFORE any array is
handed to a pool.
"""

from __future__ import annotations

import pickle
import struct
import zlib

import numpy as np

__all__ = ["KVIMAGE_VERSION", "KVImage", "KVImageError", "pack_image",
           "leaf_list"]

#: bump when the leaf layout or header schema changes; ``validate``
#: refuses images from a different version rather than guessing
KVIMAGE_VERSION = 1

#: wire framing for ``to_bytes``/``from_bytes``: magic, u16 version,
#: u8 quant, u32 metadata length (then metadata, leaf bytes, crc32)
_WIRE_MAGIC = b"KVIM"
_WIRE_HEAD = struct.Struct("!4sHBI")
_WIRE_CRC = struct.Struct("!I")


class KVImageError(ValueError):
    """A KV image failed validation (version / geometry / dtype /
    header mismatch, or arrays inconsistent with their pack-time
    header).  Raised BEFORE any scatter touches a pool — a bad image
    degrades to a cold prefill, never to corrupted cache state."""


def _leaf_list(tree):
    """Flatten a host cache pytree (array, or (values, scales) tuple,
    possibly nested under tuples/lists) into a leaf list in
    deterministic order."""
    if isinstance(tree, (tuple, list)):
        out = []
        for t in tree:
            out.extend(_leaf_list(t))
        return out
    return [tree]


def _signature(kc, vc):
    """Per-leaf (shape, dtype) header, K leaves then V leaves."""
    return tuple((tuple(a.shape), str(a.dtype))
                 for a in _leaf_list(kc) + _leaf_list(vc))


def leaf_list(tree):
    """Public alias of the leaf flattening (K leaves then V leaves is
    ``leaf_list(kc) + leaf_list(vc)``) — the dist ship path frames
    images leaf-by-leaf and must slice in the exact order the
    checksum covers."""
    return _leaf_list(tree)


def _checksum(kc, vc) -> int:
    """crc32 over every leaf's raw bytes, K leaves then V leaves —
    the content integrity the shape/dtype header cannot see."""
    crc = 0
    for a in _leaf_list(kc) + _leaf_list(vc):
        crc = zlib.crc32(np.ascontiguousarray(a).data, crc)
    return crc & 0xFFFFFFFF


class KVImage:
    """One request's (or prefix's) KV blocks as a self-describing host
    image.  Construct through :func:`pack_image` — the header is
    captured from the arrays at pack time, which is what makes
    later truncation detectable."""

    __slots__ = ("version", "block_size", "n_data", "quant", "header",
                 "kc", "vc", "checksum")

    def __init__(self, version, block_size, n_data, quant, header,
                 kc, vc, checksum=None):
        self.version = int(version)
        self.block_size = int(block_size)
        self.n_data = int(n_data)
        self.quant = bool(quant)
        self.header = tuple(header)
        self.kc = kc
        self.vc = vc
        # crc32 over the leaf bytes (None on images packed by callers
        # predating the wire round: validate then skips the content
        # check and keeps the header/geometry checks)
        self.checksum = (None if checksum is None
                         else int(checksum) & 0xFFFFFFFF)

    @property
    def width(self) -> int:
        """Lane width of the image rows (positions per leaf)."""
        return int(_leaf_list(self.kc)[0].shape[3])

    @property
    def nbytes(self) -> int:
        """Host bytes the image's arrays occupy — the fleet's
        ``serve.fleet.ship_bytes`` accounting."""
        return int(sum(a.nbytes
                       for a in _leaf_list(self.kc)
                       + _leaf_list(self.vc)))

    def validate(self, block_size, quant, pool_k=None):
        """Typed validation before any scatter: version supported,
        geometry matches the consuming arena (``block_size``,
        ``quant``), arrays consistent with the pack-time header
        (truncated or mutated images fail HERE), lane width a block
        multiple covering ``n_data`` blocks, and — when the consuming
        pool's K leaves are handed in — per-leaf dtype and
        (L, H, tail) compatibility with the pool.  Raises
        :class:`KVImageError`; returns None."""
        if self.version != KVIMAGE_VERSION:
            raise KVImageError(
                f"KV image version {self.version} != supported "
                f"{KVIMAGE_VERSION}: refuse rather than guess at the "
                f"leaf layout")
        if self.block_size != block_size:
            raise KVImageError(
                f"KV image block_size ({self.block_size}) != pool "
                f"block_size ({block_size}): lanes would not tile "
                f"the target blocks")
        if self.quant != bool(quant):
            raise KVImageError(
                f"KV image quant={self.quant} vs pool quant="
                f"{bool(quant)}: dense and int8 (values, scales) "
                f"layouts are not interchangeable")
        sig = _signature(self.kc, self.vc)
        if sig != self.header:
            raise KVImageError(
                "KV image arrays do not match their pack-time header "
                "(truncated or mutated in transit): "
                f"header={self.header} got={sig}")
        if self.checksum is not None:
            crc = _checksum(self.kc, self.vc)
            if crc != self.checksum:
                raise KVImageError(
                    f"KV image payload corrupted in transit: crc32 "
                    f"{crc:#010x} != packed {self.checksum:#010x} — "
                    f"a shape-preserving bit-flip the header check "
                    f"cannot see; refuse before any scatter")
        k_leaves = _leaf_list(self.kc)
        v_leaves = _leaf_list(self.vc)
        if len(k_leaves) != len(v_leaves):
            raise KVImageError(
                f"KV image K/V leaf-count mismatch "
                f"({len(k_leaves)} vs {len(v_leaves)})")
        W = self.width
        if W % self.block_size != 0:
            raise KVImageError(
                f"KV image lane width ({W}) is not a multiple of "
                f"block_size ({self.block_size})")
        if self.n_data < 0 or self.n_data * self.block_size > W:
            raise KVImageError(
                f"KV image n_data ({self.n_data} blocks) exceeds its "
                f"own lane width ({W} positions): a length-lying "
                f"image must never scatter")
        for a in k_leaves + v_leaves:
            if a.ndim < 4 or a.shape[1] != 1 or a.shape[3] != W:
                raise KVImageError(
                    f"KV image leaf shape {tuple(a.shape)} is not a "
                    f"(L, 1, H, {W}[, D]) cache row")
        if pool_k is not None:
            pool_leaves = _leaf_list(pool_k)
            if len(pool_leaves) != len(k_leaves):
                raise KVImageError(
                    f"KV image has {len(k_leaves)} K leaves but the "
                    f"pool has {len(pool_leaves)} (dense vs int8 "
                    f"layout drift)")
            for img, pool in zip(k_leaves, pool_leaves):
                # pool: (L, N+1, H, B, ...) vs image: (L, 1, H, W, ...)
                if (img.shape[0] != pool.shape[0]
                        or img.shape[2] != pool.shape[2]
                        or img.shape[4:] != pool.shape[4:]
                        or str(img.dtype) != str(pool.dtype)):
                    raise KVImageError(
                        f"KV image leaf {tuple(img.shape)}/{img.dtype}"
                        f" incompatible with pool leaf "
                        f"{tuple(pool.shape)}/{pool.dtype} (layer/"
                        f"head/head-dim/dtype must match)")

    def narrowed(self, n_data=None) -> "KVImage":
        """A copy of this image sliced to ``n_data`` blocks' lanes
        (default: ``self.n_data``) — the ship-path form, where bytes
        on the wire track the shipped prefix, not ``max_len``.  The
        header is re-captured from the sliced arrays (a narrowed
        image is a NEW image, not a mutation of this one)."""
        n = self.n_data if n_data is None else int(n_data)
        if n > self.n_data:
            raise KVImageError(
                f"narrowed({n}) beyond the image's n_data "
                f"({self.n_data})")
        w = max(n, 1) * self.block_size

        def cut(tree):
            if isinstance(tree, tuple):
                return tuple(cut(t) for t in tree)
            if isinstance(tree, list):
                return [cut(t) for t in tree]
            return np.ascontiguousarray(tree[:, :, :, :w])

        kc, vc = cut(self.kc), cut(self.vc)
        return KVImage(self.version, self.block_size, n, self.quant,
                       _signature(kc, vc), kc, vc,
                       checksum=_checksum(kc, vc))

    # -- wire codec (the dist transport's KV payload) --------------------
    def to_bytes(self) -> bytes:
        """Frame the image for a socket: magic + version + quant +
        length-prefixed metadata (geometry + per-leaf header), the raw
        leaf bytes in header order, and a trailing crc32 over the leaf
        bytes.  Decode with :meth:`from_bytes`."""
        leaves = [np.ascontiguousarray(a)
                  for a in _leaf_list(self.kc) + _leaf_list(self.vc)]
        meta = pickle.dumps(
            {"block_size": self.block_size, "n_data": self.n_data,
             "header": self.header,
             "k_leaves": len(_leaf_list(self.kc))},
            protocol=pickle.HIGHEST_PROTOCOL)
        crc = 0
        chunks = [_WIRE_HEAD.pack(_WIRE_MAGIC, self.version,
                                  int(self.quant), len(meta)), meta]
        for a in leaves:
            crc = zlib.crc32(a.data, crc)
            chunks.append(a.tobytes())
        chunks.append(_WIRE_CRC.pack(crc & 0xFFFFFFFF))
        return b"".join(chunks)

    @classmethod
    def from_bytes(cls, buf) -> "KVImage":
        """Decode a :meth:`to_bytes` frame.  Every malformed input is
        a typed :class:`KVImageError`: short buffers (mid-stream EOF),
        bad magic, version skew, and payload whose crc32 disagrees
        with the trailer (bit-flip in transit).  The returned image
        still goes through :meth:`validate` at the consuming pool —
        this decoder checks the WIRE, validate checks the POOL."""
        buf = memoryview(bytes(buf))
        if len(buf) < _WIRE_HEAD.size + _WIRE_CRC.size:
            raise KVImageError(
                f"KV image wire frame truncated: {len(buf)} bytes is "
                f"shorter than the fixed framing "
                f"({_WIRE_HEAD.size + _WIRE_CRC.size})")
        magic, version, quant, meta_len = _WIRE_HEAD.unpack_from(buf, 0)
        if magic != _WIRE_MAGIC:
            raise KVImageError(
                f"KV image wire frame has bad magic {bytes(magic)!r} "
                f"(expected {_WIRE_MAGIC!r}): not a KV image")
        if version != KVIMAGE_VERSION:
            raise KVImageError(
                f"KV image wire version {version} != supported "
                f"{KVIMAGE_VERSION}: refuse rather than guess at the "
                f"leaf layout")
        off = _WIRE_HEAD.size
        if len(buf) < off + meta_len + _WIRE_CRC.size:
            raise KVImageError(
                f"KV image wire frame truncated inside metadata "
                f"({len(buf)} bytes, metadata needs "
                f"{off + meta_len + _WIRE_CRC.size})")
        try:
            meta = pickle.loads(bytes(buf[off:off + meta_len]))
            header = tuple(tuple(h) for h in meta["header"])
            k_leaves = int(meta["k_leaves"])
            block_size, n_data = meta["block_size"], meta["n_data"]
        except Exception as e:
            raise KVImageError(
                f"KV image wire metadata undecodable ({e!r})") from e
        off += meta_len
        leaves = []
        for shape, dtype in header:
            n = int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
            if len(buf) < off + n + _WIRE_CRC.size:
                raise KVImageError(
                    f"KV image wire frame truncated mid-leaf: leaf "
                    f"{len(leaves)} needs {n} bytes, "
                    f"{len(buf) - off - _WIRE_CRC.size} remain "
                    f"(mid-stream EOF)")
            leaves.append(np.frombuffer(
                buf[off:off + n], dtype=dtype).reshape(shape))
            off += n
        if len(buf) != off + _WIRE_CRC.size:
            raise KVImageError(
                f"KV image wire frame has {len(buf) - off - _WIRE_CRC.size}"
                f" trailing bytes beyond its header's leaves (length-"
                f"lying frame)")
        (want,) = _WIRE_CRC.unpack_from(buf, off)
        crc = 0
        for a in leaves:
            crc = zlib.crc32(a.data, crc)
        crc &= 0xFFFFFFFF
        if crc != want:
            raise KVImageError(
                f"KV image wire payload corrupted: crc32 {crc:#010x} "
                f"!= trailer {want:#010x} (bit-flip in transit)")
        if not 0 < k_leaves < len(leaves) or k_leaves * 2 != len(leaves):
            raise KVImageError(
                f"KV image wire metadata claims {k_leaves} K leaves "
                f"of {len(leaves)} total — K/V must split evenly")

        def tree(ls):
            return ls[0] if len(ls) == 1 else tuple(ls)

        return cls(version, block_size, n_data, bool(quant), header,
                   tree(leaves[:k_leaves]), tree(leaves[k_leaves:]),
                   checksum=want)


def pack_image(kc_host, vc_host, block_size, n_data, quant) -> KVImage:
    """Seal host cache-row pytrees into a :class:`KVImage`.  The
    per-leaf header is captured HERE, so any later divergence between
    the arrays and what was packed (a truncated transfer, an in-place
    mutation) fails :meth:`KVImage.validate` typed.  Since the wire
    round the content crc32 is captured too — shape-preserving
    corruption fails the same way."""
    return KVImage(KVIMAGE_VERSION, block_size, n_data, quant,
                   _signature(kc_host, vc_host), kc_host, vc_host,
                   checksum=_checksum(kc_host, vc_host))
