"""Versioned host-side KV block images — ONE wire format for
preemption swap and fleet KV shipping (the disaggregation round).

Two paths copy paged KV between device pools and host memory:

* **preemption swap** (serve/paged.py ``swap_out``/``swap_in``) — a
  preempted request's blocks round-trip through host RAM and resume
  byte-exactly;
* **KV shipping** (serve/fleet.py disaggregated serving) — a prefill
  specialist's canonical prompt blocks travel to a decode specialist's
  pool, seeding its radix prefix cache so the admission lands warm.

Before this module the swap image was a bare ``(kc_host, vc_host)``
numpy-pytree pair with no self-description: nothing stopped a drifted
producer (or a truncated transfer) from scattering garbage into a
live pool.  A :class:`KVImage` carries a VERSION, the block geometry,
the quantization flag, and a per-leaf dtype/shape header captured at
pack time; :meth:`KVImage.validate` re-derives the signature from the
arrays and cross-checks it against both the header (truncation /
mutation fails typed) and the consuming arena's geometry (a dense
image cannot scatter into an int8 pool, a block-size-16 image cannot
land in a block-size-32 pool).  Both swap and ship consume images
through the same checks, so the two paths cannot drift.

Leaf layout (the cache-row convention every fixed-shape copy in
serve/paged.py uses): dense pools are one ``(L, 1, H_kv, W, D)``
array per K/V; int8 pools are ``(values, scales)`` tuples whose
scales leaf drops the trailing ``D`` axis.  ``W`` is the image's lane
width — a FULL row for swap (the historical shape, one executable per
engine geometry) or the narrow ``n_data * block_size`` slice for
shipping (ship bytes track the prompt, not ``max_len``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["KVIMAGE_VERSION", "KVImage", "KVImageError", "pack_image"]

#: bump when the leaf layout or header schema changes; ``validate``
#: refuses images from a different version rather than guessing
KVIMAGE_VERSION = 1


class KVImageError(ValueError):
    """A KV image failed validation (version / geometry / dtype /
    header mismatch, or arrays inconsistent with their pack-time
    header).  Raised BEFORE any scatter touches a pool — a bad image
    degrades to a cold prefill, never to corrupted cache state."""


def _leaf_list(tree):
    """Flatten a host cache pytree (array, or (values, scales) tuple,
    possibly nested under tuples/lists) into a leaf list in
    deterministic order."""
    if isinstance(tree, (tuple, list)):
        out = []
        for t in tree:
            out.extend(_leaf_list(t))
        return out
    return [tree]


def _signature(kc, vc):
    """Per-leaf (shape, dtype) header, K leaves then V leaves."""
    return tuple((tuple(a.shape), str(a.dtype))
                 for a in _leaf_list(kc) + _leaf_list(vc))


class KVImage:
    """One request's (or prefix's) KV blocks as a self-describing host
    image.  Construct through :func:`pack_image` — the header is
    captured from the arrays at pack time, which is what makes
    later truncation detectable."""

    __slots__ = ("version", "block_size", "n_data", "quant", "header",
                 "kc", "vc")

    def __init__(self, version, block_size, n_data, quant, header,
                 kc, vc):
        self.version = int(version)
        self.block_size = int(block_size)
        self.n_data = int(n_data)
        self.quant = bool(quant)
        self.header = tuple(header)
        self.kc = kc
        self.vc = vc

    @property
    def width(self) -> int:
        """Lane width of the image rows (positions per leaf)."""
        return int(_leaf_list(self.kc)[0].shape[3])

    @property
    def nbytes(self) -> int:
        """Host bytes the image's arrays occupy — the fleet's
        ``serve.fleet.ship_bytes`` accounting."""
        return int(sum(a.nbytes
                       for a in _leaf_list(self.kc)
                       + _leaf_list(self.vc)))

    def validate(self, block_size, quant, pool_k=None):
        """Typed validation before any scatter: version supported,
        geometry matches the consuming arena (``block_size``,
        ``quant``), arrays consistent with the pack-time header
        (truncated or mutated images fail HERE), lane width a block
        multiple covering ``n_data`` blocks, and — when the consuming
        pool's K leaves are handed in — per-leaf dtype and
        (L, H, tail) compatibility with the pool.  Raises
        :class:`KVImageError`; returns None."""
        if self.version != KVIMAGE_VERSION:
            raise KVImageError(
                f"KV image version {self.version} != supported "
                f"{KVIMAGE_VERSION}: refuse rather than guess at the "
                f"leaf layout")
        if self.block_size != block_size:
            raise KVImageError(
                f"KV image block_size ({self.block_size}) != pool "
                f"block_size ({block_size}): lanes would not tile "
                f"the target blocks")
        if self.quant != bool(quant):
            raise KVImageError(
                f"KV image quant={self.quant} vs pool quant="
                f"{bool(quant)}: dense and int8 (values, scales) "
                f"layouts are not interchangeable")
        sig = _signature(self.kc, self.vc)
        if sig != self.header:
            raise KVImageError(
                "KV image arrays do not match their pack-time header "
                "(truncated or mutated in transit): "
                f"header={self.header} got={sig}")
        k_leaves = _leaf_list(self.kc)
        v_leaves = _leaf_list(self.vc)
        if len(k_leaves) != len(v_leaves):
            raise KVImageError(
                f"KV image K/V leaf-count mismatch "
                f"({len(k_leaves)} vs {len(v_leaves)})")
        W = self.width
        if W % self.block_size != 0:
            raise KVImageError(
                f"KV image lane width ({W}) is not a multiple of "
                f"block_size ({self.block_size})")
        if self.n_data < 0 or self.n_data * self.block_size > W:
            raise KVImageError(
                f"KV image n_data ({self.n_data} blocks) exceeds its "
                f"own lane width ({W} positions): a length-lying "
                f"image must never scatter")
        for a in k_leaves + v_leaves:
            if a.ndim < 4 or a.shape[1] != 1 or a.shape[3] != W:
                raise KVImageError(
                    f"KV image leaf shape {tuple(a.shape)} is not a "
                    f"(L, 1, H, {W}[, D]) cache row")
        if pool_k is not None:
            pool_leaves = _leaf_list(pool_k)
            if len(pool_leaves) != len(k_leaves):
                raise KVImageError(
                    f"KV image has {len(k_leaves)} K leaves but the "
                    f"pool has {len(pool_leaves)} (dense vs int8 "
                    f"layout drift)")
            for img, pool in zip(k_leaves, pool_leaves):
                # pool: (L, N+1, H, B, ...) vs image: (L, 1, H, W, ...)
                if (img.shape[0] != pool.shape[0]
                        or img.shape[2] != pool.shape[2]
                        or img.shape[4:] != pool.shape[4:]
                        or str(img.dtype) != str(pool.dtype)):
                    raise KVImageError(
                        f"KV image leaf {tuple(img.shape)}/{img.dtype}"
                        f" incompatible with pool leaf "
                        f"{tuple(pool.shape)}/{pool.dtype} (layer/"
                        f"head/head-dim/dtype must match)")

    def narrowed(self, n_data=None) -> "KVImage":
        """A copy of this image sliced to ``n_data`` blocks' lanes
        (default: ``self.n_data``) — the ship-path form, where bytes
        on the wire track the shipped prefix, not ``max_len``.  The
        header is re-captured from the sliced arrays (a narrowed
        image is a NEW image, not a mutation of this one)."""
        n = self.n_data if n_data is None else int(n_data)
        if n > self.n_data:
            raise KVImageError(
                f"narrowed({n}) beyond the image's n_data "
                f"({self.n_data})")
        w = max(n, 1) * self.block_size

        def cut(tree):
            if isinstance(tree, tuple):
                return tuple(cut(t) for t in tree)
            if isinstance(tree, list):
                return [cut(t) for t in tree]
            return np.ascontiguousarray(tree[:, :, :, :w])

        kc, vc = cut(self.kc), cut(self.vc)
        return KVImage(self.version, self.block_size, n, self.quant,
                       _signature(kc, vc), kc, vc)


def pack_image(kc_host, vc_host, block_size, n_data, quant) -> KVImage:
    """Seal host cache-row pytrees into a :class:`KVImage`.  The
    per-leaf header is captured HERE, so any later divergence between
    the arrays and what was packed (a truncated transfer, an in-place
    mutation) fails :meth:`KVImage.validate` typed."""
    return KVImage(KVIMAGE_VERSION, block_size, n_data, quant,
                   _signature(kc_host, vc_host), kc_host, vc_host)
