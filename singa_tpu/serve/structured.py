"""Constrained structured decoding: host-side token automata driving
per-slot vocab masks (the fork round).

The problem: callers want the engine to emit ONLY outputs a grammar
accepts — JSON matching a schema, an enum choice, a bounded integer —
without a recompile per grammar and without post-hoc rejection loops.
The mechanism is the same one every production constrained-decoding
stack converged on (Outlines/SGLang/llguidance): keep the GRAMMAR
STATE on the host, and turn it into a fixed-shape ``(vocab,)`` boolean
mask applied inside the already-jitted sampling executable.  Between
steps the engine advances the automaton with the token it just
emitted and asks for the next state's mask; the device never sees the
grammar, only a mask input of constant shape — ``recompiles: 0``
holds whatever the schema.

Two pieces live here:

* :class:`TokenAutomaton` — the protocol the engine consumes
  (``GenerationRequest(structured=...)``).  States are IMMUTABLE
  values: the engine stores one state per slot, and forked branches
  (serve/fork.py) share a state snapshot at the fork point and
  advance independently — an automaton that mutated internal state on
  ``advance`` would corrupt its siblings.
* :class:`JsonSchemaAutomaton` — the shipped implementation: compiles
  a small JSON-schema subset into a CHARACTER-level program of
  literal/repeat/alternation nodes, then lifts it to token level by
  simulating each vocab token's string through the char program
  (memoized per state — the per-step cost after warmup is one dict
  hit).  Determinism is enforced at compile time: a repeat node's
  charset must be disjoint from whatever can follow it, and an
  alternation's arms must differ in their first character, so every
  (state, char) pair has at most ONE successor and ``advance`` never
  needs backtracking.  Schemas that violate this are rejected with a
  typed ValueError at construction, never inside the serve loop.

The supported schema subset is deliberately the structured-output
core: ``{"type": "object", "properties": {...}}`` with every property
required and emitted in declaration order (the canonical
fixed-key-order form function-calling APIs emit), property types
``integer`` (canonical JSON: ``0`` or a nonzero-led run of up to
max_digits digits), ``boolean``, ``string`` (bounded
alphanumeric content) and ``enum`` (distinct string choices).  The
automaton completes on the object's closing brace, at which point the
engine retires the request with ``finish_reason="stop"``.
"""

from __future__ import annotations

import numpy as np

__all__ = ["TokenAutomaton", "JsonSchemaAutomaton"]


class TokenAutomaton:
    """Protocol for ``GenerationRequest(structured=)``.

    The engine calls::

        state = a.initial()
        mask  = a.mask(state)        # (vocab_size,) bool np.ndarray
        state = a.advance(state, t)  # after emitting token t
        a.done(state)                # -> retire with "stop"

    Contract: states are immutable hashable values (forked branches
    share snapshots); ``mask`` returns a ``(vocab_size,)`` bool array
    (the engine treats the returned array as read-only and may hold
    it across steps, so memoized implementations can return one
    array per state); ``advance`` raises ``ValueError`` for a token
    the current mask disallows; ``vocab_size`` names the token space
    the masks cover — the engine type-checks it against the model's
    at submit.  Subclassing this base is optional; any object with
    the four methods and the attribute satisfies the engine."""

    vocab_size: int

    def initial(self):
        raise NotImplementedError

    def mask(self, state) -> np.ndarray:
        raise NotImplementedError

    def advance(self, state, token):
        raise NotImplementedError

    def done(self, state) -> bool:
        raise NotImplementedError


_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")


class JsonSchemaAutomaton(TokenAutomaton):
    """Char-program automaton for a JSON-schema subset.

    ``schema``: the object schema (see module docstring for the
    subset).  ``vocab``: sequence mapping token id -> the token's
    string (what detokenizing that id appends to the output); empty
    strings are never legal emissions.  ``max_digits`` bounds integer
    literals, ``max_string`` bounds free-string content — both keep
    every accepted output finite, so ``done`` is always reachable
    within a computable token budget.

    States are ``(node_idx, aux)`` tuples over the compiled node
    list: ``aux`` is the position inside a literal, the repeat count
    inside a repeat node, or ``(arm, pos)`` inside an alternation;
    ``aux is None`` means "at the node's entry, nothing consumed".
    The terminal state is ``(len(nodes), None)``.
    """

    def __init__(self, schema, vocab, max_digits=8, max_string=16):
        self._tok = [str(s) for s in vocab]
        self.vocab_size = len(self._tok)
        if self.vocab_size < 1:
            raise ValueError("vocab must be non-empty")
        self.schema = schema
        self._nodes = self._compile(schema, int(max_digits),
                                    int(max_string))
        self._check_deterministic()
        self._mask_memo = {}

    # -- schema -> char program ------------------------------------------
    @staticmethod
    def _compile(schema, max_digits, max_string):
        if not isinstance(schema, dict) \
                or schema.get("type") != "object" \
                or not isinstance(schema.get("properties"), dict) \
                or not schema["properties"]:
            raise ValueError(
                "schema must be {'type': 'object', 'properties': "
                "{...}} with at least one property (the supported "
                "structured-output subset; see serve/structured.py)")
        nodes = []
        lit = []  # pending literal chars, coalesced into one node

        def flush():
            if lit:
                nodes.append(("lit", "".join(lit)))
                del lit[:]

        props = list(schema["properties"].items())
        lit.append("{")
        for i, (name, sub) in enumerate(props):
            lit.extend(f'"{name}":')
            if isinstance(sub, dict) and "enum" in sub:
                choices = sub["enum"]
                if not choices or not all(
                        isinstance(c, str) and c for c in choices):
                    raise ValueError(
                        f"property {name!r}: enum must be non-empty "
                        f"strings, got {choices!r}")
                lit.append('"')
                flush()
                nodes.append(("alt", tuple(str(c) for c in choices)))
                lit.append('"')
            elif isinstance(sub, dict) and sub.get("type") == "integer":
                flush()
                # JSON's canonical integer: "0" alone or a nonzero
                # lead digit — a plain digit-repeat would emit "066"
                nodes.append(("int", max_digits))
            elif isinstance(sub, dict) and sub.get("type") == "boolean":
                flush()
                nodes.append(("alt", ("true", "false")))
            elif isinstance(sub, dict) and sub.get("type") == "string":
                n = int(sub.get("maxLength", max_string))
                lit.append('"')
                flush()
                nodes.append(("rep", _WORD, 0, n))
                lit.append('"')
            else:
                raise ValueError(
                    f"property {name!r}: unsupported value schema "
                    f"{sub!r} (supported: integer, boolean, string, "
                    f"enum of strings)")
            lit.append("," if i + 1 < len(props) else "}")
        flush()
        return nodes

    def _entry_chars(self, idx):
        """Chars that can be the FIRST char consumed at node ``idx``
        (following lo=0 repeats through to their successor)."""
        if idx >= len(self._nodes):
            return frozenset()
        kind = self._nodes[idx][0]
        if kind == "lit":
            return frozenset(self._nodes[idx][1][0])
        if kind == "alt":
            return frozenset(s[0] for s in self._nodes[idx][1])
        if kind == "int":
            return _DIGITS
        _, cs, lo, _hi = self._nodes[idx]
        return cs | self._entry_chars(idx + 1) if lo == 0 else cs

    def _check_deterministic(self):
        """Compile-time determinism: every (state, char) has at most
        one successor.  Repeat charsets must be disjoint from their
        successor's entry chars (otherwise "another repeat char or
        the next node?" is ambiguous) and alternation arms must
        differ in their first char."""
        for i, node in enumerate(self._nodes):
            if node[0] in ("rep", "int"):
                cs = _DIGITS if node[0] == "int" else node[1]
                clash = cs & self._entry_chars(i + 1)
                if clash:
                    raise ValueError(
                        f"ambiguous schema: repeat node {i}'s charset "
                        f"overlaps what follows it ({sorted(clash)!r})"
                        f" — the automaton could not decide when the "
                        f"repeat ends")
            elif node[0] == "alt":
                firsts = [s[0] for s in node[1]]
                if len(set(firsts)) != len(firsts):
                    raise ValueError(
                        f"ambiguous schema: alternation {node[1]!r} "
                        f"arms share a first character — choices must "
                        f"be distinguishable at their first char")

    # -- char-level stepping ---------------------------------------------
    def _enter(self, idx, ch):
        """Consume ``ch`` as the first char at node ``idx``'s entry.
        Returns the successor state or None (illegal char)."""
        if idx >= len(self._nodes):
            return None  # program complete: no char is legal
        node = self._nodes[idx]
        if node[0] == "lit":
            s = node[1]
            if ch != s[0]:
                return None
            return (idx + 1, None) if len(s) == 1 else (idx, 1)
        if node[0] == "alt":
            for a, s in enumerate(node[1]):
                if s[0] == ch:
                    return ((idx + 1, None) if len(s) == 1
                            else (idx, (a, 1)))
            return None
        if node[0] == "int":
            if ch == "0":
                return (idx + 1, None)  # "0" is a complete integer
            if ch in _DIGITS:
                return (idx + 1, None) if node[1] == 1 else (idx, 1)
            return None
        _, cs, lo, hi = node
        if ch in cs and hi >= 1:
            return (idx, 1)
        if lo == 0:
            return self._enter(idx + 1, ch)
        return None

    def _step_char(self, state, ch):
        idx, aux = state
        if aux is None:
            return self._enter(idx, ch)
        node = self._nodes[idx]
        if node[0] == "lit":
            s = node[1]
            if ch != s[aux]:
                return None
            return (idx + 1, None) if aux + 1 == len(s) else (idx,
                                                              aux + 1)
        if node[0] == "alt":
            a, pos = aux
            s = node[1][a]
            if ch != s[pos]:
                return None
            return (idx + 1, None) if pos + 1 == len(s) \
                else (idx, (a, pos + 1))
        if node[0] == "int":
            # aux digits consumed, the first was nonzero: any digit
            # extends up to max_digits, anything else exits
            if ch in _DIGITS and aux < node[1]:
                return (idx, aux + 1)
            return self._enter(idx + 1, ch)
        _, cs, lo, hi = node
        if ch in cs and aux < hi:
            return (idx, aux + 1)
        if aux >= lo:
            return self._enter(idx + 1, ch)
        return None

    def _step_token(self, state, tid):
        s = self._tok[tid]
        if not s:
            return None
        for ch in s:
            state = self._step_char(state, ch)
            if state is None:
                return None
        return state

    # -- the TokenAutomaton surface --------------------------------------
    def initial(self):
        return (0, None)

    def mask(self, state):
        m = self._mask_memo.get(state)
        if m is None:
            m = np.zeros(self.vocab_size, bool)
            for tid in range(self.vocab_size):
                if self._step_token(state, tid) is not None:
                    m[tid] = True
            self._mask_memo[state] = m
        return m

    def advance(self, state, token):
        nxt = self._step_token(state, int(token))
        if nxt is None:
            raise ValueError(
                f"token {int(token)} ({self._tok[int(token)]!r}) is "
                f"not accepted at automaton state {state!r} — the "
                f"applied mask and the emitted token disagree")
        return nxt

    def done(self, state):
        return state[0] >= len(self._nodes)
