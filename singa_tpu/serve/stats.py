"""Serving telemetry: per-request latency, queue/slot gauges, token
throughput.

Since the ``singa_tpu.observe`` round, every number here lives in the
process-wide metrics registry (``observe.registry``) instead of a
private attribute soup: counters/gauges/histograms are registered
under ``serve.*`` with an ``engine=<n>`` label (one label value per
engine instance, so two engines in one process never collide), which
makes the serving surface exportable over Prometheus text alongside
the train-side metrics without any extra glue.  The TTFT/TPOT
histograms still ride :class:`~singa_tpu.utils.metrics.LatencySeries`:
the registry's Histogram owns the series and ``self.ttft``/``self.tpot``
are the same object (one copy of the data, two views).  Engines are
process-lifetime in the registry; call :meth:`unregister` when
retiring one in a long-lived process.

The ``snapshot()`` schema is STABLE — tests/test_serve.py asserts the
exact key set, and bench_serve.py writes it into BENCH_SERVE.json so
future PRs have a comparable perf trajectory — extend it by adding
keys, never by renaming.

Metric definitions (the serving-standard ones):

* **TTFT** (time to first token): submit → the prefill token, queue
  wait included — the user-visible "how long until it starts".
* **TPOT** (time per output token): mean inter-token gap AFTER the
  first token; requests emitting one token have no TPOT sample.
  TOKENS-PER-STEP AWARE since the speculative round: the measure is
  (last token − first token) / (n − 1), which counts every token a
  step emitted, however many that was — for a speculative engine this
  IS step time / accepted tokens, so a replica whose draft stops
  agreeing (acceptance collapses toward 0, steps emit ~1 token) shows
  a proportionally worse TPOT and ``tpot_ewma``, and the fleet Router
  prices it out honestly without any speculation-specific wiring.
* **serve.request.queue_wait_s / serve.request.admission_s{kind=}**:
  the TTFT split — submit→admission (queue wait) and admission→first
  token (prefill, ``kind=cold|warm``), the same per-request numbers
  the request ledger (``observe.requests``) attributes, exported as
  bucketed Prometheus histograms so the split aggregates across a
  fleet.
* **serve.spec.{accepted,drafted}** (speculative engines only):
  draft proposals the target verify kept / offered — the realized
  acceptance rate on live traffic, the number the speculation-vs-
  unroll crossover (gpt2_decode.generate_speculative docstring) turns
  on.
* **slot occupancy**: live slots / max_slots, sampled once per decode
  step — how full the fixed-shape batch actually runs.
* **queue depth**: sampled after each step's scheduling pass.
"""

from __future__ import annotations

import itertools

from ..observe import trace as _trace
from ..observe.registry import registry
from ..utils.logging import get_channel

_engine_ids = itertools.count()


class EngineStats:
    """Accumulated over an engine's lifetime; ``snapshot()`` at any
    point.  All wall-clock numbers come from the engine's clock so a
    fake clock makes the whole schema deterministic in tests.

    ``slo``: an optional :class:`~singa_tpu.observe.health.SLO`.  When
    set, every retire is checked against its targets (per REQUEST —
    exact under any traffic shape, and strictly stronger than the
    percentile line each target guards) and every scheduling pass
    against ``queue_depth_max``; breaches increment
    ``serve.slo_violations{engine=,kind=ttft|tpot|queue}`` and emit
    trace instants (which the monitor's flight recorder captures even
    with tracing off)."""

    def __init__(self, max_slots: int, clock, reg=None, slo=None,
                 spec=False):
        self.max_slots = int(max_slots)
        self._clock = clock
        self._t0 = clock()
        reg = reg if reg is not None else registry()
        self.registry = reg
        self.engine_label = str(next(_engine_ids))
        lbl = dict(engine=self.engine_label)
        self._submitted = reg.counter(
            "serve.submitted",
            help="submit() calls (queue-full rejections included)", **lbl)
        self._completed = reg.counter(
            "serve.completed", help="requests retired normally", **lbl)
        self._rej_deadline = reg.counter(
            "serve.rejected_deadline",
            help="requests dropped past their deadline", **lbl)
        self._rej_queue = reg.counter(
            "serve.rejected_queue_full",
            help="requests rejected by back-pressure", **lbl)
        self._prefills = reg.counter(
            "serve.prefills", help="admission prefills run", **lbl)
        self._decode_steps = reg.counter(
            "serve.decode_steps", help="pool decode steps run", **lbl)
        self._tokens_out = reg.counter(
            "serve.tokens_out", help="tokens emitted", **lbl)
        self._h_ttft = reg.histogram(
            "serve.ttft", help="submit->first-token seconds", **lbl)
        self._h_tpot = reg.histogram(
            "serve.tpot", help="mean inter-token seconds", **lbl)
        self.ttft = self._h_ttft.series
        self.tpot = self._h_tpot.series
        # request-lifecycle phase histograms (the ledger's queue/
        # prefill decomposition, as aggregable Prometheus series): the
        # fed values are the SAME numbers the request ledger records
        # per timeline, so the histogram percentiles and the ledger's
        # why_slow attribution can never disagree about the population
        self._h_queue_wait = reg.histogram(
            "serve.request.queue_wait_s",
            help="submit->admission seconds (queue-wait phase of "
                 "TTFT)", **lbl)
        self._h_admission = {
            kind: reg.histogram(
                "serve.request.admission_s",
                help="admission->first-token seconds (prefill phase "
                     "of TTFT, cold vs prefix-warm)", kind=kind, **lbl)
            for kind in ("cold", "warm")}
        self._queue_depth = reg.gauge(
            "serve.queue_depth", help="scheduler queue depth", **lbl)
        self._occupancy = reg.gauge(
            "serve.occupancy",
            help="live slots / max_slots, last decode step", **lbl)
        # mean/max accumulators (a gauge only keeps the last sample)
        self._queue_depth_sum = 0
        self._queue_depth_max = 0
        self._queue_samples = 0
        self._occupancy_sum = 0.0
        self._log = get_channel("serve")
        self._registered = [
            self._submitted, self._completed, self._rej_deadline,
            self._rej_queue, self._prefills, self._decode_steps,
            self._tokens_out, self._queue_depth, self._occupancy,
            self._h_ttft, self._h_tpot, self._h_queue_wait,
            self._h_admission["cold"], self._h_admission["warm"],
        ]
        # set by the engine when a prefix cache is attached: a
        # zero-arg callable returning the cache's snapshot dict
        self.prefix_source = None
        # set by the engine in paged mode: the PagedKVArena's snapshot
        # (blocks free/used, preemption and swap counters)
        self.paged_source = None
        # set by the engine in tensor-parallel mode: the TPExecutor's
        # snapshot (shard count, per-shard KV bytes, dispatch counts)
        self.tp_source = None
        # set by the engine in expert-parallel mode (serve/ep.py): the
        # EPExecutor's snapshot (expert shard count, per-expert routed
        # token load, dropped-token count, load imbalance)
        self.ep_source = None
        # set by the engine in pipeline-parallel mode (serve/pp.py):
        # the PPExecutor's snapshot (stage count, microbatches,
        # per-stage KV bytes, dispatch counts)
        self.pp_source = None
        # speculative engines only: acceptance accounting (``spec`` is
        # set by the engine when a draft model is attached; a plain
        # engine registers nothing and snapshots spec: None)
        self.spec = bool(spec)
        self._spec_accepted = self._spec_drafted = None
        self._spec_chunks = None
        if spec:
            self._spec_accepted = reg.counter(
                "serve.spec.accepted",
                help="draft proposals the target verify kept", **lbl)
            self._spec_drafted = reg.counter(
                "serve.spec.drafted",
                help="draft proposals offered to the target verify",
                **lbl)
            self._spec_chunks = reg.counter(
                "serve.spec.chunks",
                help="per-slot verify chunks run (one per live slot "
                     "per spec step)", **lbl)
            self._registered += [self._spec_accepted,
                                 self._spec_drafted,
                                 self._spec_chunks]
        # recency-weighted TPOT (None until the first multi-token
        # retire): the fleet router's SLO-headroom signal — a replica
        # whose decode is degrading shows it here long before the
        # lifetime-mean tpot histogram moves
        self.tpot_ewma = None
        self._tpot_alpha = 0.25
        self.slo = slo
        self._slo_viol = {}
        if slo is not None:
            for kind in ("ttft", "tpot", "queue"):
                c = reg.counter(
                    "serve.slo_violations",
                    help="requests/steps beyond the declared SLO "
                         "target", kind=kind, **lbl)
                self._slo_viol[kind] = c
                self._registered.append(c)

    def unregister(self):
        """Remove this engine's metrics from the registry.  Call when
        retiring an engine in a long-lived process (per-tenant engines,
        reload loops): the registry is process-lifetime, so without
        this each discarded engine pins its serve.* set — including
        the unbounded TTFT/TPOT value lists — forever.  The stats
        object itself keeps working (snapshot() reads the same
        objects); they just stop being exported."""
        self.registry.remove(*self._registered)

    # registry-backed counts, readable as plain attributes
    @property
    def submitted(self):
        return self._submitted.value

    @property
    def completed(self):
        return self._completed.value

    @property
    def rejected_deadline(self):
        return self._rej_deadline.value

    @property
    def rejected_queue_full(self):
        return self._rej_queue.value

    @property
    def prefills(self):
        return self._prefills.value

    @property
    def decode_steps(self):
        return self._decode_steps.value

    @property
    def tokens_out(self):
        return self._tokens_out.value

    # -- recording hooks (called by the engine) -------------------------
    def on_submit(self):
        self._submitted.inc()

    def on_queue_full(self, request_id):
        self._rej_queue.inc()
        self._log.warning("queue full: rejected %s", request_id)

    def on_deadline_expired(self, request_id):
        self._rej_deadline.inc()
        self._log.warning("deadline expired: rejected %s", request_id)

    def on_prefill(self):
        self._prefills.inc()

    def on_admission(self, queue_wait_s, admission_s, warm=False):
        """One admission's latency split: ``queue_wait_s`` (submit ->
        the scheduling pass that admitted it) and ``admission_s``
        (admission -> first token, the prefill cost — labeled
        ``kind=warm`` when a prefix-cache hit skipped most of it)."""
        self._h_queue_wait.observe(queue_wait_s)
        self._h_admission["warm" if warm else "cold"].observe(
            admission_s)

    def on_token(self):
        self._tokens_out.inc()

    def on_spec(self, accepted: int, drafted: int):
        """One live slot's verify outcome: ``accepted`` of ``drafted``
        proposals kept (the +1 bonus/correction token is counted by
        ``on_token``, not here — acceptance measures the DRAFT)."""
        self._spec_accepted.inc(int(accepted))
        self._spec_drafted.inc(int(drafted))
        self._spec_chunks.inc()

    def on_decode_step(self, live_slots: int):
        self._decode_steps.inc()
        occ = live_slots / self.max_slots
        self._occupancy_sum += occ
        self._occupancy.set(occ)

    def on_schedule(self, queue_depth: int):
        self._queue_samples += 1
        self._queue_depth_sum += queue_depth
        self._queue_depth_max = max(self._queue_depth_max, queue_depth)
        self._queue_depth.set(queue_depth)
        slo = self.slo
        if (slo is not None and slo.queue_depth_max is not None
                and queue_depth > slo.queue_depth_max):
            self._slo_viol["queue"].inc()
            _trace.event("serve/queue_pressure", cat="serve",
                         depth=queue_depth,
                         limit=slo.queue_depth_max)

    def on_complete(self, result):
        self._completed.inc()
        self.ttft.record(result.ttft)
        if result.tpot is not None:
            self.tpot.record(result.tpot)
            a = self._tpot_alpha
            self.tpot_ewma = (result.tpot if self.tpot_ewma is None
                              else (1 - a) * self.tpot_ewma
                              + a * result.tpot)
        slo = self.slo
        if slo is None:
            return
        if slo.ttft_p99_s is not None and result.ttft > slo.ttft_p99_s:
            self._slo_viol["ttft"].inc()
            _trace.event("serve/slo_violation", cat="serve",
                         kind="ttft", request=result.request_id,
                         value=result.ttft, target=slo.ttft_p99_s)
        if (slo.tpot_p50_s is not None and result.tpot is not None
                and result.tpot > slo.tpot_p50_s):
            self._slo_viol["tpot"].inc()
            _trace.event("serve/slo_violation", cat="serve",
                         kind="tpot", request=result.request_id,
                         value=result.tpot, target=slo.tpot_p50_s)

    @property
    def uptime_s(self) -> float:
        """Engine-clock seconds since construction (the submit clock —
        serve health reports never recompute wall from trace events)."""
        return max(self._clock() - self._t0, 1e-9)

    @property
    def goodput_tokens_per_s(self) -> float:
        """Useful emitted tokens per wall second over the engine's
        lifetime.  ``tokens_out`` counts only tokens requests asked
        for (the engine never generates straggler padding), so this IS
        goodput, not raw device throughput."""
        return self.tokens_out / self.uptime_s

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> dict:
        wall = self.uptime_s
        return {
            "requests": {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected_deadline": self.rejected_deadline,
                "rejected_queue_full": self.rejected_queue_full,
            },
            "throughput": {
                "tokens_out": self.tokens_out,
                "wall_s": wall,
                "uptime_s": wall,
                "tokens_per_s": self.tokens_out / wall,
                # same wall read as tokens_per_s — re-reading the
                # clock via the property would make the identical-by-
                # definition pair disagree by clock jitter
                "goodput_tokens_per_s": self.tokens_out / wall,
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
            },
            "latency": {
                "ttft": self.ttft.summary(),
                "tpot": self.tpot.summary(),
                # schema extension (add-only): the router's headroom
                # signal, exposed so fleet snapshots explain routing
                "tpot_ewma_s": self.tpot_ewma,
            },
            "queue": {
                "mean_depth": (self._queue_depth_sum
                               / self._queue_samples
                               if self._queue_samples else 0.0),
                "max_depth": self._queue_depth_max,
            },
            "slots": {
                "max_slots": self.max_slots,
                "occupancy_mean": (self._occupancy_sum
                                   / self.decode_steps
                                   if self.decode_steps else 0.0),
            },
            "slo": (None if self.slo is None else {
                "targets": self.slo.asdict(),
                "violations": {k: c.value
                               for k, c in self._slo_viol.items()},
            }),
            "prefix": (self.prefix_source()
                       if self.prefix_source is not None else None),
            # add-only schema extension (paged round): None for
            # slot-arena engines; block accounting + preemption/swap
            # counters for paged ones
            "paged": (self.paged_source()
                      if self.paged_source is not None else None),
            # add-only schema extension (TP-serve round): None for
            # single-device engines; shard/mesh/dispatch accounting
            # for tensor-parallel ones (serve/tp.py)
            "tp": (self.tp_source()
                   if self.tp_source is not None else None),
            # add-only schema extensions (EP/PP-serve round): None
            # unless the engine runs the expert-parallel or
            # pipeline-parallel executor (serve/ep.py, serve/pp.py)
            "ep": (self.ep_source()
                   if self.ep_source is not None else None),
            "pp": (self.pp_source()
                   if self.pp_source is not None else None),
            # add-only schema extension (speculative round): None for
            # plain engines.  tokens_per_chunk = accepted proposals +
            # the chunk's bonus/correction token, per verify chunk —
            # the accepted-tokens/step number (slight overcount for
            # chunks the budget truncated mid-emit; acceptance itself
            # is exact)
            "spec": (None if not self.spec else {
                "drafted": self._spec_drafted.value,
                "accepted": self._spec_accepted.value,
                "chunks": self._spec_chunks.value,
                "acceptance_rate": (
                    self._spec_accepted.value / self._spec_drafted.value
                    if self._spec_drafted.value else None),
                "tokens_per_chunk": (
                    (self._spec_accepted.value + self._spec_chunks.value)
                    / self._spec_chunks.value
                    if self._spec_chunks.value else None),
            }),
        }
