"""Serving telemetry: per-request latency, queue/slot gauges, token
throughput.

Built on ``singa_tpu.utils.metrics`` (LatencySeries gives the
count/mean/p50/p99/max summary every latency here reports) and logged
through the ``serve`` channel of ``singa_tpu.utils.logging``.  The
``snapshot()`` schema is STABLE — tests/test_serve.py asserts the
exact key set, and bench_serve.py writes it into BENCH_SERVE.json so
future PRs have a comparable perf trajectory — extend it by adding
keys, never by renaming.

Metric definitions (the serving-standard ones):

* **TTFT** (time to first token): submit → the prefill token, queue
  wait included — the user-visible "how long until it starts".
* **TPOT** (time per output token): mean inter-token gap AFTER the
  first token; requests emitting one token have no TPOT sample.
* **slot occupancy**: live slots / max_slots, sampled once per decode
  step — how full the fixed-shape batch actually runs.
* **queue depth**: sampled after each step's scheduling pass.
"""

from __future__ import annotations

from ..utils.logging import get_channel
from ..utils.metrics import LatencySeries


class EngineStats:
    """Accumulated over an engine's lifetime; ``snapshot()`` at any
    point.  All wall-clock numbers come from the engine's clock so a
    fake clock makes the whole schema deterministic in tests."""

    def __init__(self, max_slots: int, clock):
        self.max_slots = int(max_slots)
        self._clock = clock
        self._t0 = clock()
        self.ttft = LatencySeries()
        self.tpot = LatencySeries()
        self.completed = 0
        self.rejected_deadline = 0
        self.rejected_queue_full = 0
        self.submitted = 0
        self.prefills = 0
        self.decode_steps = 0
        self.tokens_out = 0
        self._queue_depth_sum = 0
        self._queue_depth_max = 0
        self._queue_samples = 0
        self._occupancy_sum = 0.0
        self._log = get_channel("serve")

    # -- recording hooks (called by the engine) -------------------------
    def on_submit(self):
        self.submitted += 1

    def on_queue_full(self, request_id):
        self.rejected_queue_full += 1
        self._log.warning("queue full: rejected %s", request_id)

    def on_deadline_expired(self, request_id):
        self.rejected_deadline += 1
        self._log.warning("deadline expired: rejected %s", request_id)

    def on_prefill(self):
        self.prefills += 1

    def on_token(self):
        self.tokens_out += 1

    def on_decode_step(self, live_slots: int):
        self.decode_steps += 1
        self._occupancy_sum += live_slots / self.max_slots

    def on_schedule(self, queue_depth: int):
        self._queue_samples += 1
        self._queue_depth_sum += queue_depth
        self._queue_depth_max = max(self._queue_depth_max, queue_depth)

    def on_complete(self, result):
        self.completed += 1
        self.ttft.record(result.ttft)
        if result.tpot is not None:
            self.tpot.record(result.tpot)

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> dict:
        wall = max(self._clock() - self._t0, 1e-9)
        return {
            "requests": {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected_deadline": self.rejected_deadline,
                "rejected_queue_full": self.rejected_queue_full,
            },
            "throughput": {
                "tokens_out": self.tokens_out,
                "wall_s": wall,
                "tokens_per_s": self.tokens_out / wall,
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
            },
            "latency": {
                "ttft": self.ttft.summary(),
                "tpot": self.tpot.summary(),
            },
            "queue": {
                "mean_depth": (self._queue_depth_sum
                               / self._queue_samples
                               if self._queue_samples else 0.0),
                "max_depth": self._queue_depth_max,
            },
            "slots": {
                "max_slots": self.max_slots,
                "occupancy_mean": (self._occupancy_sum
                                   / self.decode_steps
                                   if self.decode_steps else 0.0),
            },
        }
