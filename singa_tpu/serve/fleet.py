"""Replicated serve fleet: N engine replicas behind a health-checked
router (the fleet round).

One continuous-batching engine is one process-wide failure domain: a
wedged decode, an exhausted restart budget, or a single slow replica
takes every caller down with it.  Production LLM servers (vLLM's
replicated deployments, Orca's iteration-level scheduling — the serve
layer's design references) survive replica loss by routing around it.
This module is that layer for the in-process engine:

* **fleet** — :class:`ServeFleet` owns N
  :class:`~singa_tpu.serve.supervisor.EngineSupervisor`-wrapped engine
  replicas.  Replicas share the MODEL (one copy of the weights — each
  engine's ``extract_params`` returns views of the same arrays, and
  every jitted executable is shared because the replicas are built on
  identical ``(max_slots, max_len)`` statics: an N-replica fleet
  compiles exactly once) but own their KV arena and prefix cache, so a
  replica's device state is disposable;
* **router** — :class:`Router` scores every healthy replica on
  queue depth, slot occupancy, and the TPOT EWMA from ``EngineStats``
  (a degrading replica prices itself out of new admissions before its
  latency collapses), with replicas past the SLO's ``queue_depth_max``
  penalized behind those with headroom.  ``pin_session`` continuations
  route STICKY: a :class:`~singa_tpu.serve.prefix.SessionHandle`'s next
  turn lands on the replica whose radix tree holds the pinned blocks
  (any other replica serves it cold but correct — sticky is a
  performance preference, not a correctness requirement, so a dead
  sticky target falls back to normal routing);
* **failover** — per-replica health is derived from the watchdog
  (``observe.monitor``: a replica whose heartbeat source latched a hang
  is failed over even though its supervisor never raised) and from
  typed failures (``RestartBudgetExceededError`` out of a supervisor
  that crash-looped past its budget).  A failed replica is marked
  unhealthy, its never-started requests (``started=False`` — no tokens
  streamed, same seed → same chain) are REQUEUED onto healthy siblings
  in arrival order with token-stream parity against an uninterrupted
  run, and its started requests stay typed — exactly the single-engine
  contract, now service-level.  ``revive()`` rebuilds a failed
  replica's supervisor (jit cache hit — same statics) and the router
  re-admits it;
* **degradation** — fleet-wide pressure reuses the existing
  ``shed_lowest()``/priority hooks: an arrival refused by one
  replica's SLO-pressure admission tries the next, so a request is
  only shed when NO healthy replica holds lower-priority work
  (``LoadShedError``), and when every replica is gone, submission
  fails typed (:class:`~singa_tpu.serve.request.FleetDownError`)
  instead of queueing into the void;
* **hedging** — optional (``hedge_after_steps``): a request stuck
  un-started behind a slow replica's admission for that many fleet
  steps is re-dispatched to the least-loaded sibling; first completion
  wins (identical tokens either way — same seed), the loser's work is
  the hedge's cost.  Never hedges streaming (``on_token``) or session
  requests;
* **disaggregated prefill/decode** (``roles=`` — the disagg round,
  DistServe/Splitwise-style): replicas become role-typed.  A long
  admission routes to a **prefill specialist**, which builds the
  prompt's canonical-KV block prefix with the chunked-prefill budget
  machinery (never a decode lane stalled — specialists hold none),
  then SHIPS the blocks to a **decode specialist** as a versioned
  host image (serve/kvimage.py — the swap-out format): gather on the
  source → validated image → scatter + radix-tree adoption on the
  destination → ``engine.submit`` lands as a local WARM admission,
  byte-identical to cold by the engine's warm==cold pin.  The radix
  prefix cache becomes a FLEET resource: a host-side residency index
  (:class:`~singa_tpu.serve.prefix.FleetPrefixIndex`, verified
  against live trees at use) lets a hit on ANY replica seed a
  targeted export instead of a cold re-prefill, and prefix-hash
  sticky destination routing keeps each hot prefix's blocks on as
  few replicas as possible.  Degenerate fleets fall back to mixed
  roles (1 replica, all-decode, or a dead specialist side still
  serves every request — cold, never refused), and every mid-ship
  failure (``serve.kv_ship`` fault, destination capacity, a dying
  specialist) requeues the request COLD-but-correct: nothing streams
  during a ship, so a re-route is byte-identical.

Metrics ride the process-wide observe registry as
``serve.fleet.{replicas_healthy,failovers,requeues,routed,hedges,
ships,ship_bytes,shared_prefix_hits,ship_fallbacks}`` labeled
``{fleet=,replica=}`` (the ship family fleet-wide) and surface in
``health_report()["serve"]["fleet"]``; the ``serve.route`` fault site
(singa_tpu.resilience) covers admission routing and ``serve.kv_ship``
covers both halves of a KV ship.  bench_chaos.py's ``chaos_fleet``
scenario kills a replica mid-decode and ``chaos_disagg`` kills a
prefill specialist mid-ship; CI gates on zero wedged/lost requests,
survivor parity, zero leaked blocks, and a pinned jit cache.
"""

from __future__ import annotations

import itertools
import time
import weakref
import zlib

import numpy as np

from ..observe import monitor as _monitor
from ..observe import requests as _reqs
from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..resilience import faults as _faults
from ..utils.logging import get_channel
from .paged import PagedConfig
from .prefix import FleetPrefixIndex
from .request import (EngineFailedError, FleetDownError,
                      GenerationRequest, LoadShedError, QueueFullError,
                      RequestHandle, RestartBudgetExceededError)
from .supervisor import EngineSupervisor

__all__ = ["Router", "ServeFleet"]

_fleet_ids = itertools.count()

#: score penalty for a replica past its SLO queue-depth headroom: large
#: enough to rank every pressured replica behind every unpressured one
#: (real scores are O(queue_depth)), small enough to still order the
#: pressured ones among themselves.
_PRESSURE_PENALTY = 1e6


class Router:
    """Least-loaded / SLO-headroom scoring over replica views.

    A view is the host-side load sample the fleet takes per candidate:
    ``{"replica": idx, "queue_depth": int, "occupancy": float,
    "tpot_ewma": float | None, "queue_headroom": int | None,
    "blocks_used_frac": float | None}`` (the last only on paged
    replicas — KV-pool pressure).
    ``score`` is a weighted sum — queue depth (requests ahead of this
    one), occupancy (live slots / max_slots), and the TPOT EWMA
    normalized by the fleet-wide best (a replica decoding 3x slower
    than its healthiest sibling carries a 3x term; with no samples the
    term is 0) — plus a large penalty when the replica sits at/past
    ``SLO.queue_depth_max``.  ``rank`` returns candidate indices
    best-first; ties break on LEAST-RECENTLY-ROUTED (the logical
    route tick the fleet feeds through :meth:`note_routed`), then
    replica index — deterministic, and cold traffic after a
    fleet-wide drain spreads across equal-scored replicas instead of
    piling onto replica 0.  Role-typed fleets price prefill
    specialists SEPARATELY (:meth:`score_prefill`: build-queue depth
    only — specialists hold no decode lanes, so TPOT and block
    pressure never enter their score).  Subclass and override
    ``score`` for custom policies."""

    def __init__(self, w_queue=1.0, w_occupancy=1.0, w_tpot=1.0,
                 w_blocks=1.0, w_prefill=1.0):
        self.w_queue = float(w_queue)
        self.w_occupancy = float(w_occupancy)
        self.w_tpot = float(w_tpot)
        self.w_blocks = float(w_blocks)
        self.w_prefill = float(w_prefill)
        # least-recently-routed tie-break state: replica -> logical
        # tick of its last admission (never wall time — deterministic)
        self._routed_tick = {}
        self._route_ticks = itertools.count(1)

    def note_routed(self, idx):
        """Record an admission to replica ``idx`` (the fleet calls
        this on every successful route / ship destination): the
        tie-break currency of :meth:`rank`."""
        self._routed_tick[idx] = next(self._route_ticks)

    def score(self, view, tpot_base) -> float:
        s = (self.w_queue * view["queue_depth"]
             + self.w_occupancy * view["occupancy"])
        ewma = view.get("tpot_ewma")
        if ewma is not None and tpot_base:
            s += self.w_tpot * (ewma / tpot_base)
        # paged replicas: KV-pool pressure (new admissions on a nearly
        # full pool preempt/swap — route around it before the thrash)
        blocks = view.get("blocks_used_frac")
        if blocks is not None:
            s += self.w_blocks * blocks
        headroom = view.get("queue_headroom")
        if headroom is not None and headroom <= 0:
            s += _PRESSURE_PENALTY
        return s

    def rank(self, views) -> list:
        """Replica indices best-first (ties: least-recently-routed,
        then index — see the class docstring)."""
        ewmas = [v["tpot_ewma"] for v in views
                 if v.get("tpot_ewma")]
        base = min(ewmas) if ewmas else None
        scored = sorted(
            ((self.score(v, base),
              self._routed_tick.get(v["replica"], 0),
              v["replica"]) for v in views))
        return [t[-1] for t in scored]

    def score_prefill(self, view) -> float:
        """Prefill-specialist score: the depth of ship builds queued
        on the replica — the only load a specialist carries."""
        return self.w_prefill * view.get("prefill_depth", 0)

    def rank_prefill(self, views) -> list:
        """Prefill-specialist indices best-first, same tie-break
        discipline as :meth:`rank`."""
        scored = sorted(
            ((self.score_prefill(v),
              self._routed_tick.get(v["replica"], 0),
              v["replica"]) for v in views))
        return [t[-1] for t in scored]


class _Replica:
    """Fleet-side bookkeeping for one supervised engine replica.

    ``draining``: excluded from NEW routing but still driven every
    step (it finishes its live work — the scale-down half-state).
    ``retired``: drained and closed by ``retire_replica`` — its
    engine's ``serve.*{engine=n}`` metrics are unregistered (the
    frozen-gauge fix) and it is skipped by health/snapshot until
    ``revive()`` reuses the slot."""

    __slots__ = ("idx", "sup", "healthy", "needs_failover",
                 "down_error", "draining", "retired",
                 "reconnect_deadline")

    def __init__(self, idx, sup):
        self.idx = idx
        self.sup = sup
        self.healthy = True
        self.needs_failover = False
        self.down_error = None
        self.draining = False
        self.retired = False
        # monotonic deadline while the replica's transport is inside
        # its reconnect(+grace) window: the autoscaler's _replace_dead
        # must not respawn a peer that may be about to resume
        self.reconnect_deadline = None


class _Route:
    """One fleet request's routing state: the caller-facing handle and
    every dispatch attempt ``(replica_idx, supervisor_handle)`` made
    for it (one normally; two when hedged or requeued).  A route with
    NO attempts is mid-ship (queued or building on a prefill
    specialist — the decode submission happens once the KV lands).
    ``ship_release`` pins the shipped prefix in the destination's
    radix tree until the request resolves."""

    __slots__ = ("handle", "attempts", "submit_step", "hedged",
                 "ship_release")

    def __init__(self, handle, step):
        self.handle = handle
        self.attempts = []
        self.submit_step = step
        self.hedged = False
        self.ship_release = None


class _ShipJob:
    """One disaggregated admission's prefill-and-ship state: which
    specialist is (re)building the prefix, the engine-side build, and
    whether the prefix was already RESIDENT somewhere (the
    shared-prefix-hit path — exported, never recomputed)."""

    __slots__ = ("rid", "route", "request", "src", "job", "hit")


class ServeFleet:
    """N data-parallel engine replicas behind a health-checked router.

    >>> fleet = model.serve_fleet(replicas=2, max_slots=4)
    >>> h = fleet.submit(GenerationRequest(prompt, max_new_tokens=32))
    >>> fleet.run_until_complete()
    >>> h.result().tokens     # survives a replica death in between

    ``engine_kw`` is forwarded verbatim to every replica's engine
    (``max_slots``, ``max_len``, ``slo``, ``prefix_cache``, ...);
    ``restart_budget``/``budget_reset_after_s``/``shed_on_slo_pressure``
    go to every supervisor.  Handles are fleet-owned: they resolve with
    the final outcome across restarts AND failovers.

    ``roles``: one of ``"prefill"`` / ``"decode"`` / ``"mixed"`` per
    replica (default: all mixed — the classic symmetric fleet).  Any
    role-typed fleet requires ``paged=`` and ``prefix_cache=`` in the
    engine kwargs (the ship format is the paged host image and
    cross-replica residency lives in the radix tree); disaggregated
    shipping activates when both a prefill and a decode-capable side
    exist and falls back to classic routing otherwise:

    >>> fleet = model.serve_fleet(
    ...     replicas=4, roles=("prefill", "prefill", "decode",
    ...                        "decode"),
    ...     paged=PagedConfig(block_size=16, num_blocks=96),
    ...     prefix_cache=PrefixCacheConfig(block_size=16))"""

    def __init__(self, model, replicas=2, router=None, restart_budget=2,
                 budget_reset_after_s=None, shed_on_slo_pressure=False,
                 hedge_after_steps=None, clock=time.monotonic,
                 roles=None, **engine_kw):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.roles = self._parse_roles(roles, replicas)
        self._disagg = ("prefill" in self.roles
                        and any(r != "prefill" for r in self.roles))
        self._block_size = None
        self._prefix_index = None
        self._ship_jobs = []
        if any(r != "mixed" for r in self.roles):
            pc = engine_kw.get("paged")
            cache_cfg = engine_kw.get("prefix_cache")
            if pc is None or pc is False or cache_cfg is None \
                    or cache_cfg is False:
                raise ValueError(
                    "role-typed fleets (roles=) require paged= AND "
                    "prefix_cache= in the engine kwargs: the KV ship "
                    "format is the paged host image and cross-replica "
                    "residency lives in the radix tree (docs/"
                    "SERVING.md 'Disaggregated serving')")
            if pc is True:
                pc = PagedConfig()
            elif isinstance(pc, dict):
                pc = PagedConfig(**pc)
            self._block_size = pc.block_size
            self._prefix_index = FleetPrefixIndex(pc.block_size)
        if hedge_after_steps is not None and hedge_after_steps < 1:
            raise ValueError(
                f"hedge_after_steps must be >= 1 or None, got "
                f"{hedge_after_steps}")
        if budget_reset_after_s is not None and budget_reset_after_s <= 0:
            # the supervisor would reject this too, but only after the
            # fleet registered its metrics — validate before any side
            # effect
            raise ValueError(
                f"budget_reset_after_s must be > 0 or None, got "
                f"{budget_reset_after_s}")
        self._model = model
        self._clock = clock
        self._engine_kw = dict(engine_kw)
        # tensor-parallel replicas (serve/tp.py): a fleet of TP
        # engines partitions the device mesh — replica i's shards own
        # devices [i*tp, (i+1)*tp), tensor parallelism inside each
        # replica and data parallelism across them.  Validated here
        # (tp x replicas must fit the mesh) and pinned per replica so
        # a supervisor rebuild or revive() lands on the SAME device
        # group and reuses the same compiled twins.
        self._tp_cfgs = None
        self._par_key = "tp"
        if engine_kw.get("tp") not in (None, False):
            from .tp import fleet_tp_configs

            self._tp_cfgs = fleet_tp_configs(engine_kw["tp"], replicas)
        elif engine_kw.get("ep") not in (None, False):
            # expert-parallel replicas: the mesh partitions into
            # (ep x tp)-wide groups, one per replica (serve/ep.py)
            from .ep import fleet_ep_configs

            self._par_key = "ep"
            self._tp_cfgs = fleet_ep_configs(engine_kw["ep"], replicas)
        elif engine_kw.get("pp") not in (None, False):
            # pipeline-parallel replicas: stage-wide groups
            # (serve/pp.py)
            from .pp import fleet_pp_configs

            self._par_key = "pp"
            self._tp_cfgs = fleet_pp_configs(engine_kw["pp"], replicas)
        self._sup_kw = dict(
            restart_budget=restart_budget,
            budget_reset_after_s=budget_reset_after_s,
            shed_on_slo_pressure=shed_on_slo_pressure, clock=clock)
        self.router = router if router is not None else Router()
        self._slo = engine_kw.get("slo")
        self.hedge_after_steps = hedge_after_steps
        self.fleet_label = str(next(_fleet_ids))
        self._log = get_channel("serve")
        reg = _registry()
        self._reg = reg
        lbl = dict(fleet=self.fleet_label)
        self._g_healthy = reg.gauge(
            "serve.fleet.replicas_healthy",
            help="replicas currently healthy (draining included — "
                 "they still serve their live work)", **lbl)
        self._g_routable = reg.gauge(
            "serve.fleet.replicas_routable",
            help="replicas the router currently admits NEW work to "
                 "(healthy minus draining/retired)", **lbl)
        self._c_routed, self._c_failovers = [], []
        self._c_requeues, self._c_hedges = [], []
        for i in range(replicas):
            rl = dict(lbl, replica=str(i))
            self._c_routed.append(reg.counter(
                "serve.fleet.routed",
                help="requests admitted to this replica", **rl))
            self._c_failovers.append(reg.counter(
                "serve.fleet.failovers",
                help="times this replica was failed out of the "
                     "routing set", **rl))
            self._c_requeues.append(reg.counter(
                "serve.fleet.requeues",
                help="never-started requests moved OFF this replica "
                     "onto healthy siblings", **rl))
            self._c_hedges.append(reg.counter(
                "serve.fleet.hedges",
                help="hedged re-dispatches admitted TO this replica",
                **rl))
        self._c_ships = reg.counter(
            "serve.fleet.ships",
            help="completed KV ships: a prefix built (or resident) on "
                 "one replica landed warm in another replica's pool",
            **lbl)
        self._c_ship_bytes = reg.counter(
            "serve.fleet.ship_bytes",
            help="host bytes moved by completed KV ships", **lbl)
        self._c_shared_hits = reg.counter(
            "serve.fleet.shared_prefix_hits",
            help="admissions served warm through the FLEET prefix "
                 "index — a resident prefix exported without "
                 "recompute, or routed to its resident decode replica "
                 "— instead of a cold re-prefill", **lbl)
        self._c_ship_fallbacks = reg.counter(
            "serve.fleet.ship_fallbacks",
            help="ships abandoned mid-flight (fault, capacity, "
                 "failover): the request was requeued cold-but-"
                 "correct, never lost", **lbl)
        self._registered = ([self._g_healthy, self._g_routable]
                            + self._c_routed
                            + self._c_failovers + self._c_requeues
                            + self._c_hedges
                            + [self._c_ships, self._c_ship_bytes,
                               self._c_shared_hits,
                               self._c_ship_fallbacks])
        self._replicas = [_Replica(i, self._new_supervisor(i))
                          for i in range(replicas)]
        self._refresh_gauges()
        # fleet-owned completion routing (the supervisor pattern, one
        # level up: routes resolve across restarts AND failovers)
        self._routes = {}        # request_id -> _Route
        self._order = []         # fleet arrival order (requeue order)
        # SessionHandle -> replica idx (weak: a dropped session must
        # not pin the mapping, and identity is the only safe key)
        self._sessions = weakref.WeakKeyDictionary()
        self.step_count = 0
        self._closed = False
        self._log.info(
            "fleet up: %d replicas x (slots=%d) roles=%s [fleet=%s]",
            replicas, self._replicas[0].sup.engine.max_slots,
            ",".join(self.roles), self.fleet_label)

    @staticmethod
    def _parse_roles(roles, replicas):
        if roles is None:
            return ("mixed",) * replicas
        roles = tuple(roles)
        if len(roles) != replicas:
            raise ValueError(
                f"roles has {len(roles)} entries for {replicas} "
                f"replicas — one role per replica")
        bad = sorted({r for r in roles
                      if r not in ("prefill", "decode", "mixed")})
        if bad:
            raise ValueError(
                f"unknown role(s) {bad!r}: each replica is 'prefill',"
                f" 'decode', or 'mixed'")
        return roles

    def _new_supervisor(self, idx) -> EngineSupervisor:
        """Build replica ``idx``'s supervisor — THE construction seam.
        Every path that creates replica capacity (__init__, revive(),
        add_replica()) routes through here, so a subclass that hosts
        replicas elsewhere (serve/dist/fleet.py spawns a worker
        process and returns an RPC proxy) changes exactly one
        method."""
        return EngineSupervisor(self._model, **self._sup_kw,
                                **self._replica_kw(idx))

    def _replica_kw(self, idx):
        """Engine kwargs for replica ``idx``: the shared engine_kw,
        with the sharded-backend knob (``tp``/``ep``/``pp``) swapped
        for the replica's pinned device-group config so a supervisor
        rebuild or revive() lands on the SAME group and reuses the
        same compiled twins."""
        if self._tp_cfgs is None:
            return self._engine_kw
        kw = dict(self._engine_kw)
        kw[self._par_key] = self._tp_cfgs[idx]
        return kw

    # -- introspection ---------------------------------------------------
    @property
    def replicas(self) -> int:
        """Replica slots (retired ones included — a retired slot can
        be revived, so it still counts as capacity)."""
        return len(self._replicas)

    @property
    def healthy_replicas(self) -> int:
        return sum(r.healthy for r in self._replicas)

    @staticmethod
    def _routable(rep) -> bool:
        """True when the router may send NEW work here: healthy, not
        retired, not draining toward a scale-down."""
        return rep.healthy and not rep.draining and not rep.retired

    @property
    def routable_replicas(self) -> int:
        return sum(self._routable(r) for r in self._replicas)

    def _refresh_gauges(self):
        self._g_healthy.set(self.healthy_replicas)
        self._g_routable.set(self.routable_replicas)

    @property
    def pending(self) -> bool:
        """True while any fleet-submitted request is unresolved."""
        return bool(self._routes)

    def supervisor(self, idx) -> EngineSupervisor:
        """The replica's current supervisor (tests, debuggers)."""
        return self._replicas[idx].sup

    def health(self) -> dict:
        """Per-replica health view: the router's input plus status.
        Retired replicas are DROPPED (their engines are closed and
        their metrics unregistered — a scale-down must not leave a
        frozen per-replica row behind)."""
        out = {}
        for rep in self._replicas:
            if rep.retired:
                continue
            eng = rep.sup.engine
            out[rep.idx] = {
                "healthy": rep.healthy,
                "draining": rep.draining,
                "restarts": rep.sup.restarts,
                "queue_depth": (eng.scheduler.queue_depth
                                if not eng._closed else 0),
                "live_slots": eng.live_slots if not eng._closed else 0,
                "tpot_ewma_s": eng.stats.tpot_ewma,
            }
        return out

    def load_views(self) -> list:
        """The router-signal views the fleet itself routes on (queue
        depth, occupancy, tpot_ewma, blocks_used_frac, draining flag),
        one per non-retired healthy replica — the autoscaler's input
        surface (serve/autoscale.py)."""
        return [self._view(r) for r in self._replicas
                if r.healthy and not r.retired]

    def snapshot(self) -> dict:
        """Fleet-level stats (bench_serve's ``fleet`` section).
        Retired replicas keep their lifetime ``routed`` counts (the
        fleet-labeled counters are fleet-lifetime) but contribute no
        ``engines`` entry — their engine metrics are unregistered."""
        return {
            "replicas": sum(not r.retired for r in self._replicas),
            "replicas_healthy": self.healthy_replicas,
            # add-only (autoscale round): scale-state visibility
            "replicas_routable": self.routable_replicas,
            "replicas_draining": sum(r.draining
                                     for r in self._replicas),
            "replicas_retired": sum(r.retired for r in self._replicas),
            "roles": list(self.roles),
            "failovers": sum(c.value for c in self._c_failovers),
            "requeues": sum(c.value for c in self._c_requeues),
            "hedges": sum(c.value for c in self._c_hedges),
            "routed": {str(i): c.value
                       for i, c in enumerate(self._c_routed)},
            "ships": self._c_ships.value,
            "ship_bytes": self._c_ship_bytes.value,
            "shared_prefix_hits": self._c_shared_hits.value,
            "ship_fallbacks": self._c_ship_fallbacks.value,
            "engines": [rep.sup.engine.stats.snapshot()
                        for rep in self._replicas if not rep.retired],
        }

    # -- admission -------------------------------------------------------
    def submit(self, request) -> RequestHandle:
        """Route a request to the best healthy replica.  Raises
        :class:`FleetDownError` when none is healthy,
        :class:`QueueFullError` when every healthy replica is at
        back-pressure, and :class:`LoadShedError` when SLO-pressure
        admission refuses it fleet-wide (no healthy replica holds
        lower-priority work to shed)."""
        if self._closed:
            raise RuntimeError(
                "fleet is closed; build a new one with "
                "model.serve_fleet()")
        if not isinstance(request, GenerationRequest):
            request = GenerationRequest(np.asarray(request))
        rid = request.request_id
        if rid in self._routes:
            raise ValueError(
                f"request_id {rid!r} is already in flight fleet-wide")
        if _faults._armed:
            # chaos hook: a raising router admission is a SYNCHRONOUS
            # typed failure for the caller — nothing was accepted
            _faults.check("serve.route")
        handle = RequestHandle(request)
        route = _Route(handle, self.step_count)
        prefer = None
        if self._ship_eligible(request):
            # ONE warm-target scan decides the path: resident on a
            # decode replica -> route there warm (no ship), else park
            # a ship job (the scan walks live radix trees — never pay
            # it twice on the admission hot path)
            prefer = self._warm_decode_target(request)
            if prefer is None:
                # an infeasible request (position space, worst-case
                # blocks) must fail the CALLER synchronously, exactly
                # as a direct submit would — parking it on a ship job
                # would wedge the fleet on a request no engine can
                # ever accept.  Replicas share statics, so any
                # healthy engine's feasibility check speaks for all
                idx0 = next(r.idx for r in self._replicas
                            if r.healthy)
                self._replicas[idx0].sup.engine.validate_request(
                    request)
                # disaggregated admission: the request parks on a
                # ship job (queued -> built on a prefill specialist
                # -> KV shipped) and the decode submission happens in
                # _drive_ships once the blocks land — nothing streams
                # until then, so every ship failure mode replays cold
                # with byte-identical output
                self._routes[rid] = route
                self._order.append(rid)
                self._enqueue_ship(request, route)
                return handle
        elif self._disagg:
            # not ship-eligible (short, sticky, queue-full, a side
            # down) but the fleet cache may still warm-route it
            prefer = self._warm_decode_target(request)
        try:
            idx, inner = self._route(request, prefer=prefer)
        except FleetDownError:
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="fleet_down")
            if _reqs._active:
                # no replica ever accepted it: give the request log a
                # terminal entry anyway (requests refused by a downed
                # fleet must not vanish from observability)
                _reqs._ledger.on_reject(
                    rid, t=self._clock(), reason="fleet_down",
                    started=False,
                    prompt_len=len(request.prompt_ids),
                    max_new_tokens=request.max_new_tokens)
            raise
        if _reqs._active:
            # engine.submit (inside the supervisor) opened the hop;
            # stamp WHICH replica the router chose on it
            _reqs._ledger.annotate_hop(rid, replica=idx)
        if prefer is not None and idx == prefer:
            # fleet-index warm routing: the replica's live tree holds
            # the whole shippable prefix, so this admission lands
            # warm WITHOUT a ship or a re-prefill
            self._c_shared_hits.inc()
            if _reqs._active:
                _reqs._ledger.annotate_hop(rid, shared_prefix=True)
        route.attempts.append((idx, inner))
        self._routes[rid] = route
        self._order.append(rid)
        # a replica may have died during routing (budget exhausted
        # surfacing in submit): move its work before returning
        self._drain_failovers()
        return handle

    def _route(self, request, exclude=(), prefer=None):
        """Admit ``request`` to the first candidate that takes it.
        Tries sticky, then the ``prefer`` hint (fleet-index warm
        routing), then router-ranked healthy decode-capable replicas;
        QueueFull / LoadShed at one replica falls through to the next
        (which is what makes shedding and back-pressure FLEET-wide
        decisions)."""
        last_refusal = None   # QueueFull/LoadShed from a live replica
        last_death = None     # budget exhaustion surfacing at admission
        tried = 0
        for idx in self._candidates(request, exclude, prefer):
            rep = self._replicas[idx]
            tried += 1
            try:
                inner = rep.sup.submit(request)
            except (QueueFullError, LoadShedError) as e:
                last_refusal = e
                continue
            except RestartBudgetExceededError as e:
                # the replica died between steps (failure surfaced at
                # admission): mark it down, keep routing — its
                # outstanding work moves in _drain_failovers
                self._mark_down(rep, e)
                last_death = e
                continue
            self._c_routed[idx].inc()
            nr = getattr(self.router, "note_routed", None)
            if nr is not None:
                # least-recently-routed tie-break currency (custom
                # routers without the hook simply keep index ties)
                nr(idx)
            return idx, inner
        if tried == 0 or self.healthy_replicas == 0:
            raise FleetDownError(
                f"no healthy replica ({self.healthy_replicas} of "
                f"{len(self._replicas)}); revive() one or build a new "
                f"fleet", started=False)
        if last_refusal is not None:
            # a replica dying at admission must not mask a healthy
            # sibling's back-pressure: the caller's typed error is the
            # one that describes the replicas still serving
            raise last_refusal
        raise last_death

    def _candidates(self, request, exclude=(), prefer=None):
        """Candidate replica indices, best-first: the sticky session
        target, then the warm-prefix ``prefer`` hint, then the
        router's ranking of the decode-capable pool."""
        out = []
        sess = getattr(request, "session_of", None)
        if sess is not None:
            idx = self._sessions.get(sess)
            if (idx is not None and idx not in exclude
                    and self._routable(self._replicas[idx])):
                out.append(idx)
        if (prefer is not None and prefer not in exclude
                and prefer not in out
                and self._routable(self._replicas[prefer])):
            out.append(prefer)
        views = [self._view(self._replicas[i])
                 for i in self._decode_pool(exclude)
                 if i not in out]
        out.extend(self.router.rank(views))
        return out

    def _decode_pool(self, exclude=()):
        """Replica indices decode traffic may land on: healthy
        non-prefill replicas — falling back to EVERY healthy replica
        when none exists (the degenerate-fleet mixed-role fallback: a
        1-replica, all-prefill, or dead-decode-side fleet still
        serves every request, cold but correct)."""
        out = [r.idx for r in self._replicas
               if self._routable(r) and r.idx not in exclude
               and self.roles[r.idx] != "prefill"]
        if not out:
            # degenerate-fleet fallback: a draining replica still
            # beats refusing traffic (drain is a preference, not a
            # correctness rule), but a retired one is CLOSED — never
            # a candidate
            out = [r.idx for r in self._replicas
                   if r.healthy and not r.retired
                   and r.idx not in exclude]
        return out

    def _view(self, rep) -> dict:
        eng = rep.sup.engine
        depth = eng.scheduler.queue_depth
        headroom = None
        if self._slo is not None \
                and self._slo.queue_depth_max is not None:
            headroom = self._slo.queue_depth_max - depth
        arena = eng.paged_arena
        return {
            "replica": rep.idx,
            "role": self.roles[rep.idx],
            # scale-down half-state: still serving its live work but
            # closed to new routing (the autoscaler reads this)
            "draining": rep.draining,
            "queue_depth": depth,
            "occupancy": eng.live_slots / eng.max_slots,
            "tpot_ewma": eng.stats.tpot_ewma,
            "queue_headroom": headroom,
            # role-typed fleets: ship builds queued on this replica —
            # the prefill side's load signal, priced separately from
            # every decode signal above (Router.score_prefill)
            "prefill_depth": sum(1 for s in self._ship_jobs
                                 if s.src == rep.idx),
            # paged replicas: fraction of the KV pool in use (live
            # slots + cached blocks; swapped requests hold none but
            # will re-allocate on resume) — a replica whose pool is
            # nearly full will preempt/swap new admissions, so it
            # prices itself up before the thrash starts
            "blocks_used_frac": (eng.paged_arena.blocks_used
                                 / eng.paged_arena.num_blocks
                                 if arena is not None else None),
        }

    # -- drive -----------------------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: drive every healthy replica one engine
        step, fail over replicas that died (budget exhausted) or hung
        (watchdog), requeue their never-started work onto healthy
        siblings, and hedge stuck admissions.  Returns ``pending``."""
        if self._closed:
            raise RuntimeError(
                "fleet is closed; build a new one with "
                "model.serve_fleet()")
        self._step_replicas()
        self._check_watchdog()
        self._drain_failovers()
        self._drive_ships()
        if self.hedge_after_steps is not None:
            self._maybe_hedge()
        self._sync()
        self.step_count += 1
        return self.pending

    def run_until_complete(self, max_steps=None):
        """Drive :meth:`step` until every fleet-submitted request
        resolves (normally, or typed — a fleet with dead replicas
        still terminates: work that cannot be placed is rejected, never
        parked)."""
        steps = 0
        while self.pending:
            self.step()
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(
                    f"fleet did not drain within {max_steps} steps "
                    f"(routes={len(self._routes)}, healthy="
                    f"{self.healthy_replicas}/{len(self._replicas)})")

    def _step_replicas(self):
        """Drive every healthy pending replica one engine step,
        marking down those whose restart budget surfaced.  A seam:
        serve/dist/fleet.py overrides it to issue every replica's
        step RPC before collecting any reply, so remote replicas
        decode concurrently instead of serializing on round trips."""
        for rep in self._replicas:
            if not rep.healthy or not rep.sup.pending:
                continue
            try:
                rep.sup.step()
            except RestartBudgetExceededError as e:
                self._mark_down(rep, e)

    # -- health / failover -----------------------------------------------
    def _check_watchdog(self):
        """Fail over replicas whose heartbeat source latched a hang:
        the watchdog (observe.monitor) sees a wedged engine that never
        raises — typed failures alone would miss it."""
        if not _monitor.active():
            return
        wd = _monitor.watchdog()
        if wd is None:
            return
        for rep in self._replicas:
            if not rep.healthy:
                continue
            if wd.hang_latched(rep.sup.engine._hb_source):
                self._mark_down(rep, EngineFailedError(
                    f"replica {rep.idx} hang-latched by the watchdog",
                    started=None))

    def _mark_down(self, rep, error):
        """Take a replica out of the routing set (idempotent); the
        requeue scan runs in ``_drain_failovers``."""
        if not rep.healthy:
            return
        rep.healthy = False
        rep.needs_failover = True
        rep.down_error = error
        rep.draining = False  # a dying drain is a failover, not a
        #                       scale-down — the autoscaler re-derives
        if self._prefix_index is not None:
            # the replica's tree dies with it: forget its residency
            # records (stale hints would only cost a failed verify,
            # but dropping them keeps holder scans tight)
            self._prefix_index.drop_replica(rep.idx)
        self._c_failovers[rep.idx].inc()
        self._refresh_gauges()
        self._log.error(
            "replica %d failed out of the fleet (%r); %d/%d healthy",
            rep.idx, error, self.healthy_replicas, len(self._replicas))
        _trace.event("serve/fleet_failover", cat="serve",
                     replica=rep.idx, error=repr(error),
                     healthy=self.healthy_replicas)

    def _drain_failovers(self):
        """Process every replica marked down since the last pass.  A
        requeue can itself mark another replica down (its budget
        surfaces at admission), so loop until quiescent — each replica
        fails over at most once, so this terminates."""
        progressed = True
        while progressed:
            progressed = False
            for rep in self._replicas:
                if rep.needs_failover:
                    rep.needs_failover = False
                    self._failover(rep)
                    progressed = True

    def _failover(self, rep):
        """Reject the downed replica's outstanding work typed and move
        the never-started part onto healthy siblings in arrival
        order."""
        rep.sup.abandon(repr(rep.down_error))  # no-op if already dead
        for rid in list(self._order):
            route = self._routes.get(rid)
            if route is None or route.handle.done():
                continue
            atts = [h for i, h in route.attempts if i == rep.idx]
            if not atts:
                continue
            inner = atts[-1]
            live_elsewhere = any(
                not h.done() and self._replicas[i].healthy
                for i, h in route.attempts if i != rep.idx)
            err = inner._error if inner.done() else None
            if inner.done() and err is None:
                continue  # resolved OK on this replica; _sync picks it up
            if err is None:
                # abandon() resolves every outstanding handle; an
                # unresolved one here is a routing-table bug — reject
                # typed rather than wedge the caller
                err = EngineFailedError(
                    f"{rid}: replica {rep.idx} failed over",
                    request_id=rid, started=None)
            requeue_safe = (isinstance(err, EngineFailedError)
                            and err.started is False)
            if live_elsewhere:
                continue  # a hedge is still running on a healthy sibling
            if not requeue_safe:
                _trace.event("serve/request_rejected", cat="serve",
                             request=rid, reason="failover_terminal",
                             replica=rep.idx)
                if _reqs._active:
                    _reqs._ledger.on_reject(
                        rid, t=self._clock(),
                        reason="failover_terminal",
                        started=getattr(err, "started", None))
                route.handle._reject(err)
                continue
            try:
                idx2, inner2 = self._route(route.handle.request)
            except (EngineFailedError, QueueFullError,
                    LoadShedError) as e2:
                # nowhere to put it: typed, never silently dropped.
                # EngineFailedError covers FleetDownError AND a
                # sibling's RestartBudgetExceededError surfacing at
                # admission — an escape here would leave this route
                # unresolved forever (needs_failover was already
                # cleared)
                _trace.event("serve/request_rejected", cat="serve",
                             request=rid,
                             reason="failover_unplaceable")
                if _reqs._active:
                    _reqs._ledger.on_reject(
                        rid, t=self._clock(),
                        reason=f"failover_unplaceable:"
                               f"{type(e2).__name__}",
                        started=False)
                route.handle._reject(e2)
                continue
            if _reqs._active:
                # engine.submit reopened the timeline on the sibling;
                # record the hop's cause and both replica indices
                _reqs._ledger.annotate_hop(rid, replica=idx2,
                                           via="failover",
                                           src_replica=rep.idx)
            route.attempts.append((idx2, inner2))
            self._c_requeues[rep.idx].inc()
            _trace.event("serve/fleet_requeue", cat="serve",
                         request=rid, src=rep.idx, dst=idx2)
        self._log.warning(
            "replica %d drained: never-started work requeued onto "
            "healthy siblings", rep.idx)

    def revive(self, idx):
        """Bring a failed OR retired replica back: release the dead
        engine, build a fresh supervisor (fresh restart budget, empty
        prefix cache — cold but correct; same compiled shapes, so
        reviving costs an arena allocation, not a recompile), and
        re-enter the routing set.  The autoscaler's scale-up reuses
        retired slots through exactly this path."""
        rep = self._replicas[idx]
        if rep.healthy:
            raise ValueError(f"replica {idx} is healthy")
        if not rep.sup.engine._closed:
            rep.sup.close(force=True)
        rep.sup = self._new_supervisor(idx)
        rep.healthy = True
        rep.needs_failover = False
        rep.down_error = None
        rep.draining = False
        rep.retired = False
        rep.reconnect_deadline = None
        self._refresh_gauges()
        self._log.info("replica %d revived; %d/%d healthy", idx,
                       self.healthy_replicas, len(self._replicas))
        _trace.event("serve/fleet_revive", cat="serve", replica=idx,
                     healthy=self.healthy_replicas)

    # -- elastic capacity (serve/autoscale.py drives these) --------------
    def add_replica(self, role="mixed") -> int:
        """Scale-up: append a brand-new supervised replica and admit
        it to the routing set; returns its index.  Identical statics
        mean the spawn is a COMPILE-CACHE HIT (module-wide twin/jit
        caches — the bench_serve recompile pin covers it); the cost is
        an arena allocation.  Sharded fleets (tp/ep/pp) pin their
        device groups at construction and cannot grow — scale those by
        reviving retired slots only."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        if self._tp_cfgs is not None:
            raise ValueError(
                f"cannot add a replica to a {self._par_key}-sharded "
                f"fleet: device groups were partitioned at "
                f"construction; size it max_replicas-wide up front and "
                f"scale by drain/revive (docs/SERVING.md "
                f"'Autoscaling')")
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"unknown role {role!r}: 'prefill', 'decode' or "
                f"'mixed'")
        if role != "mixed" and not self._disagg \
                and all(r == "mixed" for r in self.roles):
            raise ValueError(
                f"role={role!r} on a symmetric fleet: role-typed "
                f"replicas need the fleet built with roles= (the ship "
                f"machinery is wired at construction)")
        idx = len(self._replicas)
        # build the supervisor BEFORE registering anything fleet-side:
        # a raising constructor must not leave half a replica behind
        # (the engine's own metrics unwind through its failure paths;
        # the fleet counters below are get-or-create and cannot raise)
        sup = self._new_supervisor(idx)
        reg = self._reg
        rl = dict(fleet=self.fleet_label, replica=str(idx))
        new_counters = [
            reg.counter("serve.fleet.routed",
                        help="requests admitted to this replica",
                        **rl),
            reg.counter("serve.fleet.failovers",
                        help="times this replica was failed out of "
                             "the routing set", **rl),
            reg.counter("serve.fleet.requeues",
                        help="never-started requests moved OFF this "
                             "replica onto healthy siblings", **rl),
            reg.counter("serve.fleet.hedges",
                        help="hedged re-dispatches admitted TO this "
                             "replica", **rl),
        ]
        self._c_routed.append(new_counters[0])
        self._c_failovers.append(new_counters[1])
        self._c_requeues.append(new_counters[2])
        self._c_hedges.append(new_counters[3])
        self._registered.extend(new_counters)
        self.roles = self.roles + (role,)
        self._replicas.append(_Replica(idx, sup))
        self._refresh_gauges()
        self._log.info("replica %d added (%s); %d/%d healthy", idx,
                       role, self.healthy_replicas,
                       len(self._replicas))
        _trace.event("serve/fleet_add_replica", cat="serve",
                     replica=idx, role=role,
                     healthy=self.healthy_replicas)
        return idx

    def start_drain(self, idx):
        """Scale-down, phase 1: stop routing NEW work to the replica.
        It keeps stepping until its live requests finish
        (:meth:`drained`), then :meth:`retire_replica` closes it.
        Sticky sessions fall back to normal routing (cold but
        correct)."""
        rep = self._replicas[idx]
        if not rep.healthy or rep.retired:
            raise ValueError(f"replica {idx} is not serving")
        if rep.draining:
            return
        rep.draining = True
        self._refresh_gauges()
        self._log.info("replica %d draining (routable %d/%d)", idx,
                       self.routable_replicas, len(self._replicas))
        _trace.event("serve/fleet_drain_begin", cat="serve",
                     replica=idx, routable=self.routable_replicas)

    def cancel_drain(self, idx):
        """Abort a drain (load came back before the replica emptied):
        the replica re-enters the routing set with its state intact —
        the cheapest possible scale-up."""
        rep = self._replicas[idx]
        if not rep.draining:
            raise ValueError(f"replica {idx} is not draining")
        rep.draining = False
        self._refresh_gauges()
        _trace.event("serve/fleet_drain_cancel", cat="serve",
                     replica=idx, routable=self.routable_replicas)

    def drained(self, idx) -> bool:
        """True when a draining replica holds no work: no queued or
        live requests, and no ship build sourcing from it."""
        rep = self._replicas[idx]
        return (not rep.sup.pending
                and all(s.src != idx for s in self._ship_jobs))

    def retire_replica(self, idx):
        """Scale-down, phase 2: close a drained replica.  The close
        routes through ``EngineStats.unregister()`` (engine.close →
        _release_everything), so every ``serve.*{engine=n}`` series —
        gauges included — leaves the registry with the replica instead
        of freezing at its last value, and the health report's
        per-replica sections drop it (the leaked-gauge audit in
        tests/test_autoscale.py pins this).  The slot stays in
        ``_replicas`` so a later scale-up can ``revive()`` it on the
        same pinned config."""
        rep = self._replicas[idx]
        if rep.retired:
            return
        if not rep.draining:
            raise ValueError(
                f"replica {idx} is not draining; start_drain() first "
                f"(retire without drain would abandon live requests)")
        if not self.drained(idx):
            raise RuntimeError(
                f"replica {idx} still holds work (queue="
                f"{rep.sup.engine.scheduler.queue_depth}, live="
                f"{rep.sup.engine.live_slots}); wait for drained()")
        rep.sup.close()  # drained: the non-force close asserts it
        rep.retired = True
        rep.healthy = False
        rep.draining = False
        if self._prefix_index is not None:
            self._prefix_index.drop_replica(idx)
        self._refresh_gauges()
        self._log.info("replica %d retired; %d/%d serving", idx,
                       self.routable_replicas, len(self._replicas))
        _trace.event("serve/fleet_retire", cat="serve", replica=idx,
                     routable=self.routable_replicas)

    # -- disaggregated prefill/decode: KV shipping -----------------------
    def _ship_eligible(self, request) -> bool:
        """True when this admission should run disaggregated: a
        role-typed fleet with both sides healthy, a prompt with at
        least one shippable full block, no sticky session target,
        ship-queue headroom, and no decode replica already holding
        the prefix (that routes warm directly — cheaper than any
        ship)."""
        if not self._disagg or self._prefix_index is None:
            return False
        sess = getattr(request, "session_of", None)
        if sess is not None and self._sessions.get(sess) is not None:
            return False
        if (len(request.prompt_ids) - 1) // self._block_size < 1:
            return False
        if len(self._ship_jobs) >= self._ship_queue_max():
            # the ship queue is NOT exempt from back-pressure: past
            # the scheduler-depth bound, long admissions fall through
            # to classic routing, where the decode replicas' own
            # queue bounds and SLO shedding apply (a burst gets typed
            # QueueFullError/LoadShedError, never unbounded host
            # growth behind the specialists)
            return False
        if not any(self._routable(r) and self.roles[r.idx] == "prefill"
                   for r in self._replicas):
            return False
        return any(self._routable(r) and self.roles[r.idx] != "prefill"
                   for r in self._replicas)

    def _ship_queue_max(self) -> int:
        """Depth bound for parked ship builds: the replicas' own
        scheduler back-pressure bound (they share engine_kw), so
        disaggregated admission refuses at the same depth a direct
        engine submit would."""
        sched = self._replicas[0].sup.engine.scheduler
        return int(getattr(sched, "max_queue_depth", 64) or 64)

    def _verified_holder(self, tokens, n_goal, decode_only=False):
        """First replica whose LIVE tree verifiably holds the first
        ``n_goal`` blocks of ``tokens`` (fleet-index hint, checked
        against the tree — the ONE place the verify/prune discipline
        lives): stale hints are unregistered so later lookups stop
        paying the verify.  ``decode_only`` restricts to
        decode-capable replicas (warm routing); otherwise any role
        qualifies (targeted export).  None when nothing verifies."""
        if self._prefix_index is None or n_goal < 1:
            return None
        for idx in self._prefix_index.holders(tokens, n_goal):
            rep = self._replicas[idx]
            if not rep.healthy or (decode_only
                                   and self.roles[idx] == "prefill"):
                continue
            eng = rep.sup.engine
            if (not eng._closed and not eng._failed
                    and eng.prefix_cache is not None
                    and len(eng.prefix_cache.lookup(tokens)[:n_goal])
                    == n_goal):
                return idx
            # the replica's LRU evicted it since registration: the
            # hint is dead — prune it
            self._prefix_index.unregister(tokens, n_goal, idx)
        return None

    def _warm_decode_target(self, request):
        """A healthy decode-capable replica whose LIVE tree already
        holds the request's whole shippable prefix: routing there
        serves warm locally with no ship and no re-prefill."""
        if self._prefix_index is None:
            return None
        n_goal = (len(request.prompt_ids) - 1) // self._block_size
        return self._verified_holder(request.prompt_ids, n_goal,
                                     decode_only=True)

    def _pick_ship_src(self, request) -> int:
        """The replica a ship sources from: any healthy replica whose
        live tree already holds the whole prefix (targeted export —
        zero recompute, whatever its role), else the prefill
        specialist with the shallowest build queue."""
        n_goal = (len(request.prompt_ids) - 1) // self._block_size
        idx = self._verified_holder(request.prompt_ids, n_goal)
        if idx is not None:
            return idx
        views = [self._view(r) for r in self._replicas
                 if self._routable(r)
                 and self.roles[r.idx] == "prefill"]
        return self.router.rank_prefill(views)[0]

    def _enqueue_ship(self, request, route):
        sjob = _ShipJob()
        sjob.rid = request.request_id
        sjob.route = route
        sjob.request = request
        sjob.src = self._pick_ship_src(request)
        sjob.job = None
        sjob.hit = False
        self._ship_jobs.append(sjob)
        if _reqs._active:
            # the request's timeline opens HERE with a hop on the
            # prefill specialist: no engine.submit happens there, but
            # exact ship/prefill attribution needs the hop (this one
            # via=prefill, then the decode hop via=kv_ship)
            eng = self._replicas[sjob.src].sup.engine
            _reqs._ledger.on_submit(
                sjob.rid, engine=eng.stats.engine_label,
                t=self._clock(),
                prompt_len=len(request.prompt_ids),
                max_new_tokens=request.max_new_tokens)
            _reqs._ledger.annotate_hop(sjob.rid, replica=sjob.src,
                                       via="prefill")
        _trace.event("serve/kv_ship_queued", cat="serve",
                     request=sjob.rid, src=sjob.src)

    def _drive_ships(self):
        """Advance every queued ship one scheduling quantum: per
        healthy source, chunk its HEAD build by the specialist's own
        ``prefill_token_budget`` (None = finish this step); completed
        builds export → validate → scatter + adopt on the chosen
        decode replica, and the request submits there (warm by
        construction).  Every failure mode — an injected
        ``serve.kv_ship`` fault, a malformed image, destination
        capacity, a dying specialist — falls back to a COLD route:
        later, never wrong (nothing streamed during the ship)."""
        if not self._ship_jobs:
            return
        busy = set()
        remaining = []
        for sjob in self._ship_jobs:
            if sjob.route.handle.done():
                self._abandon_build(sjob)
                continue
            rep = self._replicas[sjob.src]
            if not rep.healthy:
                self._reassign_or_fallback(sjob, remaining)
                continue
            if sjob.src in busy:
                remaining.append(sjob)
                continue
            busy.add(sjob.src)
            try:
                if sjob.job is None \
                        or sjob.job.engine is not rep.sup.engine:
                    sjob.job = rep.sup.start_prefix_build(
                        sjob.request.prompt_ids)
                    sjob.hit = bool(sjob.job is not None
                                    and sjob.job.hit)
                if sjob.job is None:
                    self._ship_fallback(sjob, "nothing_shippable")
                    continue
                self._before_build_advance(sjob)
                done = rep.sup.advance_prefix_build(
                    sjob.job, rep.sup.engine._budget, rid=sjob.rid)
                if done is None:
                    # the specialist died mid-chunk and was rebuilt:
                    # restart the build on the fresh engine next step
                    # (nothing streamed — the replay is identical)
                    sjob.job = None
                    remaining.append(sjob)
                    continue
                if not done:
                    remaining.append(sjob)
                    continue
                self._complete_ship(sjob, rep)
            except RestartBudgetExceededError as e:
                self._mark_down(rep, e)
                self._reassign_or_fallback(sjob, remaining)
            except Exception as e:
                # mid-ship failure (injected serve.kv_ship fault, a
                # malformed/truncated image, a raising copy): the
                # engine helpers already unwound their local state —
                # requeue the request cold-but-correct
                self._log.warning(
                    "ship for %s failed (%r); serving cold", sjob.rid,
                    e)
                self._ship_fallback(sjob, type(e).__name__)
        self._ship_jobs = remaining
        if any(r.needs_failover for r in self._replicas):
            self._drain_failovers()

    def _ship_dsts(self, request) -> list:
        """Ship destination candidates, best-first: the PREFIX-HASH
        STICKY target (a deterministic crc32 of the shipped block
        prefix over the healthy decode pool, so each hot prefix's
        blocks concentrate on as few replicas as possible), then the
        router's ranking of the rest."""
        pool = self._decode_pool()
        if not pool:
            return []
        n_goal = (len(request.prompt_ids) - 1) // self._block_size
        toks = np.asarray(request.prompt_ids, np.int32).reshape(-1)
        key = toks[:n_goal * self._block_size].tobytes()
        sticky = sorted(pool)[zlib.crc32(key) % len(pool)]
        out = [sticky]
        views = [self._view(self._replicas[i]) for i in pool
                 if i != sticky]
        out.extend(self.router.rank(views))
        return out

    def _before_build_advance(self, sjob):
        """Hook called just before each ship build's advance quantum.
        A no-op here; serve/dist/fleet.py uses it to open the
        layer-wise STREAMED ship (pick the destination, start its
        staging, attach the frame sink) so KV lanes ship while the
        source is still prefilling later chunks."""

    def _complete_ship(self, sjob, src_rep):
        """Transfer a finished build: export the image from the
        source, land it on the first destination with capacity, and
        submit the request there (the admission finds the prefix in
        its OWN radix tree — a local warm hit)."""
        req = sjob.request
        t0 = self._clock()
        image, src_resident = src_rep.sup.export_prefix_image(
            sjob.job)
        sjob.job = None
        n = image.n_data
        if src_resident:
            # only a REAL donation/residency is worth indexing — a
            # pool-pressure export-from-row never entered the tree
            self._prefix_index.register(req.prompt_ids, n,
                                        src_rep.idx)
        path = dst_rep = None
        for idx in self._ship_dsts(req):
            cand = self._replicas[idx]
            try:
                path = cand.sup.admit_prefix_image(req.prompt_ids,
                                                   image)
            except RestartBudgetExceededError as e:
                self._mark_down(cand, e)
                continue
            if path is not None:
                dst_rep = cand
                break
        if path is None:
            self._ship_fallback(sjob, "dst_capacity")
            return
        self._land_shipped(sjob, src_rep, dst_rep, path, n,
                           image.nbytes, t0)

    def _land_shipped(self, sjob, src_rep, dst_rep, path, n, nbytes,
                      t0):
        """Final leg of any completed ship (bulk image OR streamed
        frames): submit the request on the destination — where the
        admission is a local warm hit — pin the shipped prefix for the
        request's lifetime, and account the ship."""
        req = sjob.request
        t1 = self._clock()
        dst = dst_rep.idx
        cache = dst_rep.sup.engine.prefix_cache
        try:
            inner = dst_rep.sup.submit(req)
        except (QueueFullError, LoadShedError, ValueError,
                RestartBudgetExceededError) as e:
            # refused AFTER the blocks landed: they stay CACHED on
            # the destination (soft free space, not a leak) — unpin
            # and serve cold wherever the router finds room
            try:
                cache.release(path)
            except RuntimeError:
                pass
            if isinstance(e, RestartBudgetExceededError):
                self._mark_down(dst_rep, e)
            self._ship_fallback(sjob, "dst_refused")
            return
        sjob.route.ship_release = (cache, path)
        sjob.route.attempts.append((dst, inner))
        self._c_routed[dst].inc()
        nr = getattr(self.router, "note_routed", None)
        if nr is not None:
            nr(dst)
        self._c_ships.inc()
        self._c_ship_bytes.inc(nbytes)
        if sjob.hit:
            # the prefix was RESIDENT on the source (an earlier
            # build, another request's donation): this ship recomputed
            # nothing — the fleet-level cache did its job
            self._c_shared_hits.inc()
        self._prefix_index.register(req.prompt_ids, n, dst)
        if _reqs._active:
            _reqs._ledger.annotate_hop(
                sjob.rid, replica=dst, via="kv_ship",
                src_replica=src_rep.idx, ship_s=t1 - t0,
                ship_bytes=nbytes, ship_blocks=n)
        _trace.event("serve/kv_ship", cat="serve", request=sjob.rid,
                     src=src_rep.idx, dst=dst, blocks=n,
                     bytes=nbytes)
        self._log.info("shipped %d KV blocks for %s: replica %d -> %d"
                       " (%d bytes)", n, sjob.rid, src_rep.idx, dst,
                       nbytes)

    def _ship_fallback(self, sjob, reason):
        """Serve a failed ship COLD: nothing streamed during the
        ship, so a plain re-route is byte-identical — later, never
        wrong.  Unplaceable requests reject typed (the failover
        contract), never silently dropped."""
        self._abandon_build(sjob)
        self._c_ship_fallbacks.inc()
        rid = sjob.rid
        _trace.event("serve/kv_ship_fallback", cat="serve",
                     request=rid, reason=reason)
        try:
            idx, inner = self._route(sjob.request)
        except (EngineFailedError, QueueFullError, LoadShedError,
                ValueError) as e:
            # ValueError: submit-time infeasibility surfacing on the
            # cold path (belt and braces — ship eligibility already
            # pre-validated, but the route must NEVER let an escape
            # wedge the drive loop with the job gone)
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="ship_unplaceable")
            if _reqs._active:
                _reqs._ledger.on_reject(
                    rid, t=self._clock(),
                    reason=f"ship_unplaceable:{type(e).__name__}",
                    started=False)
            sjob.route.handle._reject(e)
            return
        if _reqs._active:
            _reqs._ledger.annotate_hop(rid, replica=idx,
                                       via="ship_fallback",
                                       reason=reason)
        sjob.route.attempts.append((idx, inner))

    def _reassign_or_fallback(self, sjob, remaining):
        """The build's source replica died: restart it on another
        healthy prefill specialist (nothing streamed — a rebuilt
        prefix is byte-identical), else serve cold."""
        self._abandon_build(sjob)
        have_prefill = any(
            self._routable(r) and self.roles[r.idx] == "prefill"
            for r in self._replicas)
        have_decode = any(
            self._routable(r) and self.roles[r.idx] != "prefill"
            for r in self._replicas)
        if have_prefill and have_decode:
            sjob.src = self._pick_ship_src(sjob.request)
            sjob.job = None
            if _reqs._active:
                eng = self._replicas[sjob.src].sup.engine
                _reqs._ledger.on_submit(sjob.rid,
                                        engine=eng.stats.engine_label,
                                        t=self._clock())
                _reqs._ledger.annotate_hop(sjob.rid, replica=sjob.src,
                                           via="prefill")
            _trace.event("serve/kv_ship_requeued", cat="serve",
                         request=sjob.rid, src=sjob.src)
            remaining.append(sjob)
        else:
            self._ship_fallback(sjob, "specialist_lost")

    def _abandon_build(self, sjob):
        """Release a job's engine-side refs (idempotent; a rebuilt
        engine makes it a no-op — the old tree died with it)."""
        if sjob.job is not None:
            rep = self._replicas[sjob.src]
            if sjob.job.engine is rep.sup.engine:
                rep.sup.abandon_prefix_build(sjob.job)
            sjob.job = None

    def _release_ship_pin(self, route):
        if route.ship_release is not None:
            cache, path = route.ship_release
            route.ship_release = None
            try:
                cache.release(path)
            except RuntimeError:
                pass  # the destination engine was rebuilt: stale path

    # -- hedging ---------------------------------------------------------
    def _maybe_hedge(self):
        """Re-dispatch requests stuck un-started behind one replica's
        admission for ``hedge_after_steps`` fleet steps.  Only
        non-streaming, non-session requests hedge (a duplicate stream
        would double tokens at the client; a session belongs to its
        replica), and only once per request."""
        for rid in self._order:
            route = self._routes.get(rid)
            if (route is None or route.handle.done() or route.hedged
                    or len(route.attempts) != 1):
                continue
            req = route.handle.request
            if (req.on_token is not None or req.pin_session
                    or getattr(req, "session_of", None) is not None):
                continue
            if self.step_count - route.submit_step \
                    < self.hedge_after_steps:
                continue
            idx0, inner0 = route.attempts[0]
            rep0 = self._replicas[idx0]
            if inner0.done():
                continue
            if rid in rep0.sup.engine.live_request_ids:
                continue  # started: it is decoding, not stuck
            try:
                idx2, inner2 = self._route(req, exclude={idx0})
            except (EngineFailedError, QueueFullError, LoadShedError):
                continue  # nowhere better to run it; not an error
            if _reqs._active:
                # the hedge twin is a CONCURRENT hop on the same
                # timeline (engine labels disambiguate its events)
                _reqs._ledger.annotate_hop(rid, replica=idx2,
                                           via="hedge",
                                           src_replica=idx0)
            route.attempts.append((idx2, inner2))
            route.hedged = True
            self._c_hedges[idx2].inc()
            _trace.event("serve/fleet_hedge", cat="serve", request=rid,
                         src=idx0, dst=idx2,
                         waited_steps=self.step_count
                         - route.submit_step)
            self._log.info("hedged %s: replica %d -> %d after %d "
                           "steps un-started", rid, idx0, idx2,
                           self.step_count - route.submit_step)

    # -- completion routing ----------------------------------------------
    def _sync(self):
        """Propagate resolved attempts to the fleet handles.  First
        success wins (hedged twins produce identical tokens — same
        seed, same chain); a route rejects only once EVERY attempt has
        failed and no requeue replaced it."""
        done = []
        for rid, route in self._routes.items():
            h = route.handle
            if h.done():
                done.append(rid)
                continue
            finished = None
            err = None
            all_done = True
            for idx, inner in route.attempts:
                if not inner.done():
                    all_done = False
                    continue
                if inner._error is None:
                    finished = (idx, inner._result)
                    break
                err = inner._error
            if finished is not None:
                idx, result = finished
                if result.session is not None:
                    # sticky routing source: this session's blocks live
                    # in replica idx's radix tree
                    self._sessions[result.session] = idx
                h._finish(result)
                done.append(rid)
            elif all_done and err is not None:
                h._reject(err)
                done.append(rid)
        if done:
            for rid in done:
                route = self._routes.pop(rid, None)
                if route is not None:
                    # a shipped request's prefix pin lives exactly as
                    # long as the request: release it with the route
                    self._release_ship_pin(route)
            live = set(self._routes)
            self._order = [r for r in self._order if r in live]

    # -- lifecycle -------------------------------------------------------
    def close(self):
        """Retire the fleet: close every replica (force for abandoned
        ones and hedge losers — their fleet handles are resolved; the
        leftover supervisor-side work is nobody's) and unregister the
        fleet metrics.  Requires every FLEET handle resolved
        (``not pending``)."""
        if self._closed:
            return
        if self.pending:
            raise RuntimeError(
                f"close() with {len(self._routes)} requests in flight;"
                f" drain with run_until_complete() first")
        for rep in self._replicas:
            if not rep.sup.engine._closed:
                rep.sup.close(force=True)
        self._reg.remove(*self._registered)
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is None:
            self.close()
        elif not self._closed:
            # release registries + arenas without masking the in-flight
            # exception behind the drained-first check
            for rep in self._replicas:
                if not rep.sup.engine._closed:
                    rep.sup.engine.__exit__(exc_type, *a)
            self._reg.remove(*self._registered)
            self._closed = True
        return False
