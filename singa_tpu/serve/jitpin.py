"""Serve-wide jit-cache census: the recompile pin, as a library call.

``bench_serve`` has always pinned "zero runtime recompiles" by counting
jit-cache entries across every executable the serve stack dispatches
(engine prefill/decode, prefix cache, paged arena + its AOT cost-table
cache, and the tp/ep/pp sharded-twin caches) before and after the timed
runs.  The federation round needs that same census ACROSS THE PROCESS
BOUNDARY — a ``DistFleet`` worker reports its own count over the
telemetry op so a 2-process bench can prove the warm path compiled
nothing — so the counter lives here in the library and the benches
import it.

Returns ``None`` (never a guess) when the running jax build does not
expose ``_cache_size`` — callers report "unavailable" instead of a
false pin.
"""


def jit_cache_size():
    """Total jit-cache entries across every serve executable in THIS
    process, or ``None`` if the jax build can't count them."""
    from singa_tpu.serve import engine as E
    from singa_tpu.serve import paged as G
    from singa_tpu.serve import prefix as P
    from singa_tpu.serve import tp as T

    total = 0
    for f in (E._pool_decode_step, E._pool_spec_step, E._prefill_one,
              E._prefill_batch, E._prefill_rows, E._write_slot,
              E._chunk_row,
              E._first_from_hidden, P._blocks_to_row,
              P._row_to_blocks, P._read_slot, G._paged_decode_step,
              G._paged_spec_step, G._paged_decode_kernel,
              G._paged_spec_kernel, G._pool_to_row, G._row_to_pool,
              G._rows_to_pool):
        try:
            total += f._cache_size()
        except Exception:
            return None  # jax without _cache_size: report honestly
    twins = T._twin_cache_size()
    if twins is None:
        return None
    from singa_tpu.serve import ep as EPM
    from singa_tpu.serve import pp as PPM

    ep_twins = EPM._twin_cache_size()
    pp_twins = PPM._twin_cache_size()
    if ep_twins is None or pp_twins is None:
        return None
    return (total + G._compile_cache_size() + twins + ep_twins
            + pp_twins)
