"""Signal-driven fleet autoscaling: the action half of the
telemetry→decision→action loop (ROADMAP item 5a).

Everything below the autoscaler already exists: the fleet's Router
signals (queue depth, occupancy, ``tpot_ewma``, ``blocks_used_frac``
— ``ServeFleet.load_views``), the windowed burn-rate state
(``observe.slo.SLOPolicy``), and the elastic-capacity primitives
(``add_replica``/``revive``/``start_drain``/``retire_replica``).
This module is the policy that closes the loop:

* **scale-up** when the error budget is burning (any firing burn-rate
  rule) or the load signals clear their high-water marks — by
  CANCELLING an in-flight drain first (free capacity), then reviving
  a retired slot (compile-cache hit on the pinned config), then
  appending a brand-new replica (also a cache hit: identical statics
  — ``recompiles: 0`` is bench-pinned across spawns);
* **scale-down** when every signal sits below its low-water mark, no
  alert is firing, and the cooldowns have passed — by DRAINING the
  least-loaded replica (stop routing → wait for its live requests →
  ``retire_replica``, which routes through ``EngineStats.unregister``
  so no ``{engine=n}`` series freezes in the registry);
* **flap control** — separate up/down cooldowns, low/high hysteresis
  bands on every signal, one drain in flight at a time, and a
  scale-down embargo for ``scale_down_cooldown_s`` after any scale-up.

Every decision — acted on, retried, or abandoned — lands in the
structured :attr:`Autoscaler.scaling_events` ledger with the full
signal snapshot that justified it, so an autoscale is as explainable
after the fact as a slow request is through the request ledger.  The
``serve.autoscale`` fault site (singa_tpu.resilience) is checked
BEFORE any replica construction or registration: an injected failure
mid-scale-up abandons the decision typed (ledger ``action:
"scale_up_failed"``), leaves no half-registered replica, and the
next :meth:`check` simply retries.

Threadless by design (the ``Watchdog.check()`` idiom): the owner
calls :meth:`check` from its drive loop with an injectable clock, so
the whole decision table is deterministic under test.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass

from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["AutoscaleConfig", "Autoscaler"]


@dataclass(frozen=True)
class AutoscaleConfig:
    """Scaling policy knobs.  The high/low pairs are hysteresis
    bands: scale-up triggers ABOVE high, scale-down requires EVERY
    signal below its low — between the bands the fleet holds steady.

    * ``queue_high``/``queue_low``: mean scheduler queue depth per
      routable replica;
    * ``occupancy_high``/``occupancy_low``: mean live-slot occupancy;
    * ``blocks_high``: max paged-pool used fraction (None or unpaged
      engines skip the signal);
    * ``prefill_queue_high``/``prefill_queue_low``: mean SHIP-BUILD
      queue depth per routable prefill specialist (the disagg round's
      separate load signal — specialists hold no decode lanes, so
      queue/occupancy/TPOT never see their pressure); role-less fleets
      have no prefill views and skip the signal;
    * ``scale_up_cooldown_s``/``scale_down_cooldown_s``: minimum
      spacing between same-direction actions; a scale-down is also
      embargoed for ``scale_down_cooldown_s`` after any scale-up
      (never retire the capacity a burst just bought).
    """

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 120.0
    queue_high: float = 4.0
    queue_low: float = 0.5
    occupancy_high: float = 0.85
    occupancy_low: float = 0.35
    blocks_high: float = 0.85
    prefill_queue_high: float = 2.0
    prefill_queue_low: float = 0.5

    def validate(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if self.scale_up_cooldown_s < 0 \
                or self.scale_down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        for low, high, name in (
                (self.queue_low, self.queue_high, "queue"),
                (self.prefill_queue_low, self.prefill_queue_high,
                 "prefill_queue"),
                (self.occupancy_low, self.occupancy_high,
                 "occupancy")):
            if low < 0 or high <= low:
                raise ValueError(
                    f"need 0 <= {name}_low < {name}_high, got "
                    f"low={low} high={high}")
        if self.blocks_high is not None \
                and not 0.0 < self.blocks_high <= 1.0:
            raise ValueError(
                f"blocks_high must be in (0, 1] or None, got "
                f"{self.blocks_high}")


class Autoscaler:
    """Scale a :class:`~singa_tpu.serve.fleet.ServeFleet` between
    ``min_replicas`` and ``max_replicas`` off its own routing signals
    plus the installed burn-rate policy.

    >>> policy = observe.slo.SLOPolicy(slo, budget_frac=0.05)
    >>> scaler = Autoscaler(fleet, AutoscaleConfig(max_replicas=4),
    ...                     slo_policy=policy)
    >>> while serving:
    ...     fleet.step()
    ...     policy.poll()
    ...     scaler.check()

    ``slo_policy`` may be None (pure load-signal scaling).  Metrics
    ride the registry as ``serve.autoscale.*{fleet=}`` and surface in
    ``health_report()["serve"]["autoscale"]``; every decision is a
    ``serve/autoscale`` trace instant AND a structured entry in
    :attr:`scaling_events`."""

    def __init__(self, fleet, config=None, slo_policy=None,
                 clock=None, reg=None):
        self.fleet = fleet
        self.config = config if config is not None else AutoscaleConfig()
        self.config.validate()
        if fleet.replicas < self.config.min_replicas:
            raise ValueError(
                f"fleet has {fleet.replicas} replicas, below "
                f"min_replicas={self.config.min_replicas} — build the "
                f"fleet at least min-wide")
        self.slo_policy = slo_policy
        self.clock = (clock if clock is not None
                      else getattr(fleet, "_clock", time.monotonic))
        reg = reg if reg is not None else _registry()
        self.registry = reg
        lbl = dict(fleet=fleet.fleet_label)
        self._g_replicas = reg.gauge(
            "serve.autoscale.replicas",
            help="replicas the autoscaler currently targets as "
                 "serving (routable + draining)", **lbl)
        self._g_min = reg.gauge(
            "serve.autoscale.min_replicas",
            help="configured scale floor", **lbl)
        self._g_max = reg.gauge(
            "serve.autoscale.max_replicas",
            help="configured scale ceiling", **lbl)
        self._g_draining = reg.gauge(
            "serve.autoscale.draining",
            help="replicas mid-drain toward retirement", **lbl)
        self._c_ups = reg.counter(
            "serve.autoscale.scale_ups",
            help="replicas added/revived/drain-cancelled by the "
                 "autoscaler", **lbl)
        self._c_downs = reg.counter(
            "serve.autoscale.scale_downs",
            help="replicas drained and retired by the autoscaler",
            **lbl)
        self._c_failed = reg.counter(
            "serve.autoscale.decisions_failed",
            help="scaling actions abandoned typed (serve.autoscale "
                 "fault, constructor failure); retried on a later "
                 "check", **lbl)
        self._registered = [
            self._g_replicas, self._g_min, self._g_max,
            self._g_draining, self._c_ups, self._c_downs,
            self._c_failed]
        self._g_min.set(self.config.min_replicas)
        self._g_max.set(self.config.max_replicas)
        #: structured decision ledger: dicts of {t, action, replica,
        #: reason, signals} (actions: scale_up, scale_up_failed,
        #: drain_begin, drain_cancelled, drain_done) — the SOAK.json
        #: evidence trail
        self.scaling_events = []
        self._last_up_t = None
        self._last_down_t = None
        self._draining_idx = None
        self._closed = False
        self._log = get_channel("serve")
        self._refresh_gauges()

    # -- signal gathering ------------------------------------------------
    def signals(self, now=None) -> dict:
        """One JSON-able snapshot of everything the decision reads:
        per-replica router views aggregated + burn-rate state."""
        all_views = [v for v in self.fleet.load_views()
                     if not v["draining"]]
        # prefill specialists carry NO decode load (their queue depth
        # and occupancy are structurally 0) — folding them into the
        # decode means would dilute real pressure, so the roles see
        # separate aggregates (role-less fleets: pviews is empty and
        # nothing changes)
        pviews = [v for v in all_views if v.get("role") == "prefill"]
        views = [v for v in all_views if v.get("role") != "prefill"]
        n = len(views)
        q = [v["queue_depth"] for v in views]
        occ = [v["occupancy"] for v in views]
        blocks = [v["blocks_used_frac"] for v in views
                  if v.get("blocks_used_frac") is not None]
        ewmas = [v["tpot_ewma"] for v in views
                 if v.get("tpot_ewma") is not None]
        pq = [v.get("prefill_depth", 0) for v in pviews]
        pol = self.slo_policy
        return {
            "routable": len(all_views),
            "draining": self._draining_idx,
            "queue_depth_mean": (sum(q) / n) if n else 0.0,
            "queue_depth_max": max(q) if q else 0,
            "occupancy_mean": (sum(occ) / n) if n else 0.0,
            "occupancy_max": max(occ) if occ else 0.0,
            "blocks_used_frac_max": max(blocks) if blocks else None,
            "tpot_ewma_max_s": max(ewmas) if ewmas else None,
            "prefill_routable": len(pviews),
            "prefill_depth_mean": (sum(pq) / len(pq)) if pq else 0.0,
            "prefill_depth_max": max(pq) if pq else 0,
            "alerts_firing": ([name for name, st in pol.alerts.items()
                               if st["firing"]]
                              if pol is not None else []),
        }

    # -- the decision loop -----------------------------------------------
    def check(self, now=None):
        """One threadless decision pass: finish an in-flight drain,
        then evaluate scale-up (burn alert or high-water load), then
        scale-down (all-quiet + cooldowns).  Returns the ledger entry
        it appended, or None when the fleet holds steady."""
        if self._closed:
            raise RuntimeError("autoscaler is closed")
        if now is None:
            now = self.clock()
        event = None
        self._sync_drain_state()
        sig = self.signals(now)
        event = self._replace_dead(now, sig)
        if event is not None:
            self._refresh_gauges()
            return event
        up_reasons = self._up_reasons(sig)
        if up_reasons:
            # pressure is evaluated BEFORE a finished drain retires:
            # load returning just as the drain empties takes the free
            # cancel_drain path instead of paying retire + respawn
            if self._can_scale_up(sig, now):
                event = self._scale_up(now, sig, up_reasons)
        elif self._draining_idx is not None \
                and self.fleet.drained(self._draining_idx):
            event = self._finish_drain(now, sig)
        elif self._can_scale_down(sig, now):
            event = self._begin_drain(now, sig)
        self._refresh_gauges()
        return event

    def _sync_drain_state(self):
        """A draining replica that FAILED over (or was revived by
        hand) is no longer ours to retire."""
        idx = self._draining_idx
        if idx is None:
            return
        rep = self.fleet._replicas[idx]
        if not (rep.healthy and rep.draining):
            self._draining_idx = None

    def _up_reasons(self, sig) -> list:
        cfg = self.config
        reasons = []
        if sig["alerts_firing"]:
            reasons.append("slo_burn:" + ",".join(sig["alerts_firing"]))
        if sig["queue_depth_mean"] > cfg.queue_high:
            reasons.append("queue_depth")
        if sig["occupancy_mean"] > cfg.occupancy_high:
            reasons.append("occupancy")
        if (cfg.blocks_high is not None
                and sig["blocks_used_frac_max"] is not None
                and sig["blocks_used_frac_max"] > cfg.blocks_high):
            reasons.append("kv_blocks")
        if (sig["prefill_routable"] > 0
                and sig["prefill_depth_mean"] > cfg.prefill_queue_high):
            # build-queue pressure on the prefill side: a separate
            # signal with a separate remedy (a prefill specialist, not
            # a decode replica — _scale_role picks it)
            reasons.append("prefill_queue")
        return reasons

    def _can_scale_up(self, sig, now) -> bool:
        cfg = self.config
        if self._draining_idx is not None:
            return True  # cancelling a drain is always available
        if sig["routable"] >= cfg.max_replicas:
            return False
        return (self._last_up_t is None
                or now - self._last_up_t >= cfg.scale_up_cooldown_s)

    def _can_scale_down(self, sig, now) -> bool:
        cfg = self.config
        if self._draining_idx is not None:
            return False  # one drain in flight at a time
        if sig["routable"] <= cfg.min_replicas:
            return False
        if sig["alerts_firing"]:
            return False
        if sig["queue_depth_mean"] > cfg.queue_low \
                or sig["occupancy_mean"] > cfg.occupancy_low:
            return False
        if sig["prefill_routable"] > 0 \
                and sig["prefill_depth_mean"] > cfg.prefill_queue_low:
            return False  # ship builds still queued: not all-quiet
        if self._last_down_t is not None \
                and now - self._last_down_t < cfg.scale_down_cooldown_s:
            return False
        # never retire capacity a burst just bought
        if self._last_up_t is not None \
                and now - self._last_up_t < cfg.scale_down_cooldown_s:
            return False
        return True

    # -- actions ---------------------------------------------------------
    def _record(self, now, action, replica, reason, sig, error=None,
                **extra):
        entry = {"t": now, "action": action, "replica": replica,
                 "reason": reason, "signals": sig}
        if error is not None:
            entry["error"] = error
        entry.update(extra)
        self.scaling_events.append(entry)
        _trace.event("serve/autoscale", cat="serve", action=action,
                     replica=replica, reason=reason)
        return entry

    def _scale_role(self, reasons) -> str:
        """Which ROLE the pressure calls for: prefill-only pressure
        wants a prefill specialist, anything decode-side on a
        disaggregated fleet wants a decode replica, and symmetric
        fleets always grow mixed (the only role add_replica accepts
        there)."""
        if not getattr(self.fleet, "_disagg", False):
            return "mixed"
        if reasons == ["prefill_queue"]:
            return "prefill"
        return "decode"

    def _scale_up(self, now, sig, reasons):
        reason = "+".join(reasons)
        fleet = self.fleet
        role = self._scale_role(reasons)
        # a drain in flight IS spare capacity: cancelling it is
        # cheaper than any spawn, and it cannot fail — but a drain is
        # always decode-side capacity, so prefill-only pressure skips
        # the cancel and buys an actual specialist (unless the spawn
        # gates — ceiling or cooldown — block it, where the cancel is
        # still strictly better than holding)
        prefill_can_spawn = (
            role == "prefill"
            and sig["routable"] < self.config.max_replicas
            and (self._last_up_t is None
                 or now - self._last_up_t
                 >= self.config.scale_up_cooldown_s))
        if self._draining_idx is not None and not prefill_can_spawn:
            idx = self._draining_idx
            fleet.cancel_drain(idx)
            self._draining_idx = None
            self._last_up_t = now
            self._c_ups.inc()
            self._log.info("autoscale: drain of replica %d cancelled "
                           "(%s)", idx, reason)
            return self._record(now, "drain_cancelled", idx, reason,
                                sig, role=role)
        try:
            # the fault site guards the WHOLE action: fired here,
            # nothing was constructed or registered — the decision
            # aborts typed and a later check retries it
            if _faults._armed:
                _faults.check("serve.autoscale")
            roles = getattr(fleet, "roles", None)
            retired = [r.idx for r in fleet._replicas if r.retired]
            # prefer a retired slot whose pinned role MATCHES the
            # pressure (reviving a decode slot does nothing for a
            # backed-up prefill side), then any retired slot for
            # mixed growth, then a fresh spawn with the right role
            match = [i for i in retired
                     if roles is None or roles[i] == role]
            if match:
                idx = match[0]
                fleet.revive(idx)
                how = "revive"
            elif retired and role == "mixed":
                idx = retired[0]
                fleet.revive(idx)
                how = "revive"
            else:
                idx = fleet.add_replica(role=role)
                how = "spawn"
        except Exception as e:
            self._c_failed.inc()
            self._log.warning("autoscale: scale-up abandoned (%r); "
                              "will retry", e)
            return self._record(now, "scale_up_failed", None, reason,
                                sig, error=repr(e), role=role)
        self._last_up_t = now
        self._c_ups.inc()
        self._log.info("autoscale: scale-up via %s -> %s replica %d "
                       "(%s)", how, role, idx, reason)
        return self._record(now, "scale_up", idx,
                            f"{reason} via={how}", sig, role=role)

    @staticmethod
    def _in_reconnect_grace(rep) -> bool:
        """A distributed replica still inside its transport reconnect
        (+grace) window must not be respawned: the worker may be about
        to resume its session, and a concurrent revive would
        double-spawn the replica index.  The deadline is on
        ``time.monotonic`` (transport time, not the autoscaler's
        signal clock) and cleared on resume / revive."""
        deadline = getattr(rep, "reconnect_deadline", None)
        return deadline is not None and time.monotonic() < deadline

    def _replace_dead(self, now, sig):
        """Replace a FAILED (not retired — those are deliberate
        scale-downs) replica: revive it on its pinned config so the
        fleet heals back to its pre-failure width without waiting for
        load pressure.  Runs before the pressure evaluation — a dead
        replica is lost capacity whatever the signals say — but
        respects the scale-up cooldown so a crash-looping replica
        cannot drive a revive storm.  Replicas inside a reconnect
        grace window are skipped (see ``_in_reconnect_grace``)."""
        fleet = self.fleet
        cfg = self.config
        if sig["routable"] >= cfg.max_replicas:
            return None
        if self._last_up_t is not None \
                and now - self._last_up_t < cfg.scale_up_cooldown_s:
            return None
        dead = [r for r in fleet._replicas
                if not r.healthy and not r.retired
                and not getattr(r, "needs_failover", False)
                and not self._in_reconnect_grace(r)]
        if not dead:
            return None
        rep = dead[0]
        roles = getattr(fleet, "roles", None)
        role = roles[rep.idx] if roles is not None else "mixed"
        try:
            if _faults._armed:
                _faults.check("serve.autoscale")
            fleet.revive(rep.idx)
        except Exception as e:
            self._c_failed.inc()
            self._log.warning(
                "autoscale: dead-replica replacement abandoned (%r); "
                "will retry", e)
            return self._record(now, "replace_failed", rep.idx,
                                "replica_dead", sig, error=repr(e),
                                role=role)
        self._last_up_t = now
        self._c_ups.inc()
        self._log.info("autoscale: dead %s replica %d replaced", role,
                       rep.idx)
        return self._record(now, "replace_dead", rep.idx,
                            "replica_dead", sig, role=role)

    def _begin_drain(self, now, sig):
        fleet = self.fleet
        # least-loaded routable victim: fewest queued + live requests
        # (cheapest to drain); prefill specialists are skipped — their
        # load is ship builds, priced separately
        cands = [v for v in fleet.load_views()
                 if not v["draining"] and v.get("role") != "prefill"]
        if len(cands) <= self.config.min_replicas:
            return None
        view = min(cands, key=lambda v: (v["queue_depth"]
                                         + v["occupancy"],
                                         -v["replica"]))
        idx = view["replica"]
        try:
            if _faults._armed:
                _faults.check("serve.autoscale")
            fleet.start_drain(idx)
        except Exception as e:
            self._c_failed.inc()
            return self._record(now, "scale_down_failed", idx,
                                "all_quiet", sig, error=repr(e))
        self._draining_idx = idx
        self._log.info("autoscale: draining replica %d toward "
                       "retirement", idx)
        return self._record(now, "drain_begin", idx, "all_quiet", sig)

    def _finish_drain(self, now, sig):
        idx = self._draining_idx
        try:
            self.fleet.retire_replica(idx)
        except RuntimeError:
            return None  # raced new work into the replica; keep waiting
        self._draining_idx = None
        self._last_down_t = now
        self._c_downs.inc()
        self._log.info("autoscale: replica %d retired", idx)
        return self._record(now, "drain_done", idx, "drained", sig)

    def _refresh_gauges(self):
        fleet = self.fleet
        serving = sum(r.healthy and not r.retired
                      for r in fleet._replicas)
        self._g_replicas.set(serving)
        self._g_draining.set(sum(r.draining for r in fleet._replicas))

    # -- reporting / lifecycle -------------------------------------------
    def section(self) -> dict:
        """JSON-able autoscaler state (SOAK.json's ``autoscale``
        key; the health report's section is registry-derived so it
        works cross-process, this one is richer)."""
        return {
            "enabled": True,
            "config": asdict(self.config),
            "replicas_serving": int(self._g_replicas.value),
            "draining": self._draining_idx,
            "scale_ups": self._c_ups.value,
            "scale_downs": self._c_downs.value,
            "decisions_failed": self._c_failed.value,
            "events": list(self.scaling_events),
        }

    def close(self):
        """Unregister the autoscaler's metrics (the fleet and any
        in-flight drain are left exactly as they are — closing the
        policy must not mutate capacity)."""
        if self._closed:
            return
        self.registry.remove(*self._registered)
        self._closed = True
