"""Tensor-parallel sharded serving: one engine's weights and KV
memory partitioned across a ``tp`` device mesh (the TP-serve round;
Megatron-LM intra-layer partitioning applied to the paged serve
engine — ROADMAP item 1's second half, after PR 6's data-parallel
fleet).

A fleet of replicas scales REQUESTS, but every replica still holds a
full weight copy and a full KV arena, so the largest servable model is
whatever fits one device.  This module shards ONE engine instead:

* **execution model** — every engine executable (pool decode, spec
  chunk, admission prefill, warm chunk prefill, slot/row copies, the
  paged pool steps, swap in/out) gains a SHARDED TWIN: the same jitted
  function body run under ``jax.shard_map`` over a 1-D ``tp`` mesh
  (``parallel.sharding.create_tp_mesh``), with the Megatron layout
  from ``parallel.tensor_parallel.decode_param_specs`` — attention
  heads and MLP columns column-partitioned (local, no communication),
  attention out-proj and MLP fc2 row-partitioned closing with ONE
  ``lax.psum`` each (``gpt2_decode._tp_psum`` — 2 collectives per
  layer per step, recorded with axis name + mesh size so Chrome traces
  can attribute them);
* **sharded KV** — each shard owns a ``(L, num_blocks+1, H_kv/tp,
  block_size, D)`` slice of the paged block pool (and of the int8
  scales leaf, slot arenas, prefix-cache pool, and every cache row):
  ``decode_cache_spec`` pins the KV-head axis, which is ALWAYS axis 2,
  whatever the leaf rank.  Block ids are global — a pool block is the
  same logical block on every shard — so the host-side free list,
  block tables, radix tree, preemption/swap bookkeeping, scheduler,
  and request ledger are untouched and see a single logical engine;
* **replicated everything else** — embeddings, LayerNorms, the LM
  head, sampling, and the whole DRAFT model (speculative decoding)
  run replicated: every shard computes identical tokens from identical
  post-psum activations, so the twin's outputs need no gather and any
  draft geometry is legal at any tp width;
* **parity** — TP streams are pinned token-identical to the
  single-device engine (tests/test_tp_serve.py: cold/warm/int8/GQA/
  speculative/preempt-resume, greedy and seeded sampling).  The psum
  is the one arithmetic difference (the row-parallel contraction is
  summed per shard, then reduced), so per-position logits agree to
  float addition-order, not bitwise — on token streams that is
  identity away from exact argmax/CDF ties, the same near-tie caveat
  ``generate_speculative`` documents;
* **swap parity across shards** — ``swap_out`` gathers the sharded row
  to ONE host copy with the full head axis (``np.asarray`` assembles
  the global array), so a preempted TP request's host image is
  byte-compatible with the single-device engine's and resume restores
  it shard-exactly.

Twins are cached MODULE-WIDE keyed on (twin, mesh devices, statics) —
a supervisor rebuild or an identical fleet replica reuses the same
compiled executables, keeping the restart-is-a-cache-hit contract;
``bench_serve.py``'s recompile pin counts this cache too.  Every
sharded dispatch checks the ``serve.tp_collective`` fault site
(singa_tpu.resilience): an injected fault is a raising sharded step —
the engine fails TYPED and the supervisor rebuilds the sharded engine
(bench_chaos.py ``chaos_tp`` gates zero wedged/lost requests).

Metrics ride the observe registry as ``serve.tp.{shards,
collectives_per_step,kv_bytes_per_shard,sharded_dispatches}{engine=}``
and surface in ``health_report()["serve"]["tp"]``.

Scope: dense/GQA models (``n_head``, ``n_kv_head``, and ``n_inner``
must divide by ``tp``).  MoE blocks shard over the EXPERT axis, not
tp, and models carrying a training ``ShardingPlan`` own their layout
already — both rejected typed at construction.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observe import trace as _trace
from ..observe.registry import registry as _default_registry
from ..parallel.sharding import TP as TP_AXIS
from ..parallel.sharding import create_tp_mesh
from ..parallel.tensor_parallel import (decode_cache_spec,
                                        decode_param_specs)
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["TPConfig", "TPExecutor", "fleet_tp_configs"]

#: replicated spec (host scalars, token/pos/live vectors, draft state,
#: sampling keys — everything the twins do not shard)
_R = P()
#: every KV leaf: head axis (axis 2) over the tp mesh
_CS = decode_cache_spec(TP_AXIS)

# module-wide twin cache: (base, extra statics, executor key) -> jitted
# sharded executable.  Engines, supervisor rebuilds, and same-device
# fleet replicas with identical geometry share one entry, so a restart
# is a jit-cache hit exactly like the single-device engine's contract.
_TWINS = {}


def _twin_cache_size():
    """Compiled-signature count across every cached TP twin —
    ``bench_serve._serve_jit_cache_size`` adds this to the recompile
    pin so the sharded dispatch path cannot recompile unnoticed."""
    total = 0
    for f in _TWINS.values():
        try:
            total += f._cache_size()
        except Exception:
            return None
    return total


@dataclass(frozen=True)
class TPConfig:
    """Knobs for the tensor-parallel serve backend (hand to
    ``model.serve(tp=...)`` — a bare int is shorthand for
    ``TPConfig(tp=k)``; the supervisor/fleet forward it verbatim so a
    rebuilt replica lands on the SAME device group and reuses the same
    compiled twins).

    ``tp``: shard count (the mesh width; 1 = tensor parallelism off).
    ``devices``: explicit device tuple (default: the first ``tp`` of
    ``jax.devices()``) — the fleet hands each TP replica a disjoint
    slice (:func:`fleet_tp_configs`).
    ``ring_prefill``: RING-ATTENTION prefill for cold long-prompt
    admissions (the long-context round): the prompt's sequence axis
    shards over the SAME tp mesh and K/V blocks rotate the ICI ring
    (``parallel/ring_attention.ring_self_attention``, causal), so
    prefill attention workspace per shard is O((S/tp)^2) — prompts
    beyond one shard's flash tile stop being the admission
    bottleneck.  The ring path keeps a REPLICATED full-weight copy
    (context parallelism: sequence sharded, weights whole — the
    attention heads cannot stay Megatron-column-sharded when the
    visiting K/V block carries a different rank's sequence chunk),
    so it trades one extra weight copy for the sequence-memory win;
    composition limits (no prefix cache, no sliding window, no int8)
    are typed at engine construction — docs/SERVING.md "Long-context
    serving".
    ``ring_min_tokens``: only prompts at least this long take the
    ring path (shorter ones stay on the serial narrow-width
    prefill, which is cheaper than paying ppermute latency)."""

    tp: int = 2
    devices: tuple | None = None
    ring_prefill: bool = False
    ring_min_tokens: int = 256

    def __post_init__(self):
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.devices is not None \
                and len(self.devices) < self.tp:
            raise ValueError(
                f"TPConfig(tp={self.tp}) with only "
                f"{len(self.devices)} explicit devices")
        if self.ring_min_tokens < 0:
            raise ValueError(
                f"ring_min_tokens must be >= 0, got "
                f"{self.ring_min_tokens}")


def as_tp_config(tp):
    """Normalize the ``tp=`` knob (bare int shard count, kwargs dict,
    or a TPConfig) to a TPConfig — the ONE coercion the engine and
    the fleet both apply, so what they accept cannot diverge."""
    if isinstance(tp, TPConfig):
        return tp
    if isinstance(tp, int) and not isinstance(tp, bool):
        return TPConfig(tp=tp)
    if isinstance(tp, dict):
        return TPConfig(**tp)
    raise ValueError(
        f"tp must be an int shard count, a TPConfig, or a kwargs "
        f"dict, got {type(tp)}")


def fleet_tp_configs(tp, replicas, devices=None):
    """Disjoint per-replica :class:`TPConfig`\\ s for a fleet of TP
    engines: replica ``i`` owns devices ``[i*tp, (i+1)*tp)`` — tensor
    parallelism inside each replica, data parallelism across them.
    Raises when ``tp x replicas`` exceeds the mesh: TP shards must not
    time-share a device with another replica's shards (on the CPU
    virtual mesh that would silently serialize the fleet)."""
    tp = as_tp_config(tp)
    if tp.tp == 1:
        return [tp] * replicas
    devs = (list(tp.devices) if tp.devices is not None
            else list(jax.devices()))
    need = tp.tp * replicas
    if need > len(devs):
        raise ValueError(
            f"tp x replicas ({tp.tp} x {replicas} = {need}) exceeds "
            f"the {len(devs)}-device mesh; shrink the fleet or the tp "
            f"width, or provision a larger virtual mesh via XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need}")
    return [TPConfig(tp=tp.tp,
                     devices=tuple(devs[i * tp.tp:(i + 1) * tp.tp]))
            for i in range(replicas)]


class TPExecutor:
    """The engine's pluggable sharded executor: owns the ``tp`` mesh,
    the Megatron weight placement, the sharded-twin dispatch, and the
    ``serve.tp.*`` metrics.  Built by ``InferenceEngine`` when
    ``tp=`` is set; the engine routes every target-side dispatch
    through the methods below (the default ``_LocalExec`` routes them
    to the single-device executables instead — engine.py)."""

    def __init__(self, config, cfg, statics, quant, model_plan=None,
                 engine_label="0", reg=None):
        if model_plan is not None:
            raise ValueError(
                "tp= on a plan-sharded model: the training "
                "ShardingPlan already owns the weight layout; build "
                "the serve model without a plan and let the TP "
                "backend place the decode weights")
        if getattr(cfg, "moe_every", None) is not None:
            raise NotImplementedError(
                "tp= on an MoE model: expert weights shard over the "
                "expert axis, not the tensor-parallel axis — serve "
                "this model with model.serve(ep=EPConfig(ep=, tp=)) "
                "(singa_tpu/serve/ep.py: expert-parallel decode with "
                "the dense layers on an orthogonal tp axis); bare "
                "tp= covers dense/GQA models")
        tp = int(config.tp)
        # mesh first: "tp wider than the machine" is the clearer error
        # when both it and a divisibility check would fire
        self.mesh = create_tp_mesh(tp, devices=config.devices)
        for what, n in (("n_head", cfg.n_head),
                        ("n_kv_head (H_kv)", cfg.n_kv_head),
                        ("n_inner", cfg.n_inner)):
            if n % tp != 0:
                raise ValueError(
                    f"tp={tp} does not divide {what} ({n}): every "
                    f"shard must own a whole number of heads/columns "
                    f"(and the KV arena slice is (..., H_kv/tp, ...))")
        self.config = config
        self.tp = tp
        self.n_layer = int(cfg.n_layer)
        self._statics = dict(statics)
        self._quant = bool(quant)
        self._spec = None      # (spec_k, (dn, de, dm)) once set_spec
        self._chunk = None     # chunk statics dict once set_chunk
        self._window = None    # sliding window once set_window
        self._ring_params = None   # replicated copy once enable_ring
        self._top = None
        self._pspec = None     # set by place_params
        self._cache_sh = NamedSharding(self.mesh, _CS)
        self._repl_sh = NamedSharding(self.mesh, _R)
        self._kv_bytes = 0
        self._log = get_channel("serve")
        # twin identity: device group + the engine statics every twin
        # bakes in (per-twin extras — block size, spec/chunk statics —
        # ride the twin key's `extra` slot).  place_params appends the
        # param pytree's treedef: the in_specs closures bake _pspec in,
        # so two models with identical statics on the same devices but
        # different tree STRUCTURE (layer count, head tying) must not
        # share a twin — the cached spec tree would be a mismatched
        # prefix for the second model's params.
        self._key = (tp,
                     tuple(int(d.id) for d in self.mesh.devices.flat),
                     tuple(sorted(self._statics.items())),
                     self._quant)
        reg = reg if reg is not None else _default_registry()
        lbl = dict(engine=engine_label)
        self._lbl = lbl
        self._g_shards = reg.gauge(
            "serve.tp.shards",
            help="tensor-parallel shard count of this engine's mesh",
            **lbl)
        self._g_coll = reg.gauge(
            "serve.tp.collectives_per_step",
            help="psums one decode dispatch issues (2 per layer: "
                 "attention out-proj + MLP fc2)", **lbl)
        self._g_kv = reg.gauge(
            "serve.tp.kv_bytes_per_shard",
            help="persistent KV-cache bytes each shard holds (its "
                 "H_kv/tp slice of every arena/pool this engine "
                 "placed)", **lbl)
        self._c_dispatch = reg.counter(
            "serve.tp.sharded_dispatches",
            help="sharded-twin executions (decode/spec/prefill/copy/"
                 "swap dispatches that ran under shard_map)", **lbl)
        self._g_shards.set(tp)
        self._g_coll.set(2 * self.n_layer)
        self._g_kv.set(0)
        self._registered = [self._g_shards, self._g_coll, self._g_kv,
                            self._c_dispatch]
        self._registry = reg
        self._log.info("tp executor up: %d shards over %s", tp,
                       [str(d) for d in self.mesh.devices.flat])

    # -- placement --------------------------------------------------------
    def place_params(self, params):
        """Lay the extracted decode weights out Megatron-style over
        the mesh (column q/k/v/fc1, row out-proj/fc2, everything else
        replicated — ``decode_param_specs``).  Also derives the
        in-spec pytree every twin uses for its params argument."""
        self._pspec = decode_param_specs(params, axis=TP_AXIS)
        self._key = self._key + (jax.tree.structure(params),)
        # None leaves (the tied-weights head) are empty subtrees in
        # BOTH pytrees, so tree.map skips them and the placed dict
        # keeps its None where the original had one
        return jax.tree.map(
            lambda a, s: jax.device_put(
                a, NamedSharding(self.mesh, s)), params, self._pspec)

    def place_cache(self, tree):
        """Place a KV pytree (arena/pool/row; dense or (values,
        scales)) sharded on its head axis, and account its per-shard
        bytes in ``serve.tp.kv_bytes_per_shard``."""
        placed = jax.tree.map(
            lambda a: jax.device_put(a, self._cache_sh), tree)
        self._kv_bytes += sum(a.nbytes
                              for a in jax.tree.leaves(tree)) // self.tp
        self._g_kv.set(self._kv_bytes)
        return placed

    def place_replicated(self, tree):
        """Commit a pytree replicated across the mesh (draft params
        and arenas, sampling keys): every shard reads its own copy and
        the twins' ``P()`` in-specs never re-broadcast per dispatch."""
        return jax.tree.map(
            lambda a: jax.device_put(a, self._repl_sh), tree)

    # -- late statics -----------------------------------------------------
    def set_spec(self, spec_k, d_statics):
        self._spec = (int(spec_k), tuple(d_statics))

    def set_chunk(self, chunk_statics):
        self._chunk = dict(chunk_statics)

    def set_window(self, window):
        """Sliding-window width (or None) — a STATIC every prefill
        and block-kernel twin bakes in, so it rides each twin's
        ``extra`` key slot (two engines for the same weights with
        different windows must not share a twin)."""
        self._window = None if window is None else int(window)

    def enable_ring(self, host_params):
        """Arm ring-attention prefill: commit a REPLICATED full-weight
        copy for the sequence-sharded twin (the Megatron column shards
        cannot serve it — a visiting K/V block carries another rank's
        sequence chunk for ALL heads) and register the dispatch
        counter.  The engine runs the composition checks before
        calling this (no prefix cache / window / int8)."""
        self._ring_params = self.place_replicated(host_params)
        self._c_ring = self._registry.counter(
            "serve.tp.ring_prefills",
            help="cold admissions prefilled via ring attention "
                 "(sequence sharded over the tp mesh)", **self._lbl)
        self._registered.append(self._c_ring)
        self.ring_prefills = 0

    # -- twin dispatch ----------------------------------------------------
    def _twin(self, base, extra, make, donate=()):
        key = (base, extra, self._key)
        fn = _TWINS.get(key)
        if fn is None:
            fn = jax.jit(
                jax.shard_map(make(), mesh=self.mesh,
                              in_specs=self._in_specs(base),
                              out_specs=self._out_specs(base),
                              check_vma=False),
                donate_argnums=donate)
            _TWINS[key] = fn
        return fn

    def _dispatch(self, fn, *args):
        """Run a twin: the ``serve.tp_collective`` fault site (an
        injected fault is a raising sharded step — the engine fails
        typed, the supervisor rebuilds), the dispatch counter, and a
        ``serve/compile`` trace instant whenever this call compiled a
        new signature (jit-cache-size delta: serve-side compiles must
        not be invisible)."""
        if _faults._armed:
            _faults.check("serve.tp_collective")
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        out = fn(*args)
        if before is not None and fn._cache_size() != before:
            _trace.event("serve/compile", cat="serve", fn="serve.tp",
                         shards=self.tp)
        self._c_dispatch.inc()
        return out

    def _in_specs(self, base):
        ps = self._pspec
        return {
            "pool_decode": (ps, _CS, _CS, _R, _R, _R, _R, _R, _R),
            "pool_spec": (ps, _R, _CS, _CS, _R, _R, _R, _R, _R, _R,
                          _R, _R),
            "prefill_one": (ps, _R, _R, _R, _R, _R),
            "prefill_batch": (ps, _R, _R, _R, _R, _R),
            "chunk_row": (ps, _R, _CS, _CS, _R),
            "paged_decode": (ps, _CS, _CS, _R, _R, _R, _R, _R, _R,
                             _R),
            "paged_spec": (ps, _R, _CS, _CS, _R, _R, _R, _R, _R, _R,
                           _R, _R, _R),
            "write_slot": (_CS, _CS, _CS, _CS, _R),
            "read_slot": (_CS, _CS, _R),
            "pool_to_row": (_CS, _CS, _R, _R),
            "row_to_pool": (_CS, _CS, _CS, _CS, _R),
            "rows_to_pool": (_CS, _CS, _CS, _CS, _R, _R),
            # ring prefill: replicated weights, SEQUENCE-sharded ids
            "ring_prefill": (_R, P(None, TP_AXIS)),
        }[base]

    def _out_specs(self, base):
        return {
            "pool_decode": (_R, _CS, _CS, _R),
            "pool_spec": (_R, _R, _CS, _CS, _R, _R, _R),
            "prefill_one": (_R, _R, _CS, _CS),
            "prefill_batch": (_R, _R, _CS, _CS),
            "chunk_row": (_R, _CS, _CS),
            "paged_decode": (_R, _CS, _CS, _R),
            "paged_spec": (_R, _R, _CS, _CS, _R, _R, _R),
            "write_slot": (_CS, _CS),
            "read_slot": (_CS, _CS),
            "pool_to_row": (_CS, _CS),
            "row_to_pool": (_CS, _CS),
            "rows_to_pool": (_CS, _CS),
            # (hidden, kc_row, vc_row) — everything sharded on the
            # SEQUENCE axis; ring_prefill_one re-places afterwards
            "ring_prefill": (P(None, TP_AXIS, None),
                             P(None, None, None, TP_AXIS, None),
                             P(None, None, None, TP_AXIS, None)),
        }[base]

    # -- the executor surface (mirrors engine._LocalExec) -----------------
    def pool_decode_step(self, params, kc, vc, toks, pos, live, keys,
                         temps, top_p):
        from functools import partial

        from .engine import _pool_decode_step

        fn = self._twin(
            "pool_decode", (),
            lambda: partial(_pool_decode_step.__wrapped__,
                            **self._statics, tp_axis=TP_AXIS,
                            tp_world=self.tp),
            donate=(1, 2))
        return self._dispatch(fn, params, kc, vc, toks, pos, live,
                              keys, temps, top_p)

    def pool_spec_step(self, t_params, d_params, kc, vc, dkc, dvc,
                       toks, pos, live, keys, temps, top_p):
        from functools import partial

        from .engine import _pool_spec_step

        st = self._statics
        spec_k, (dn, de, dm) = self._spec
        fn = self._twin(
            "pool_spec", (spec_k, dn, de, dm),
            lambda: partial(_pool_spec_step.__wrapped__, spec_k=spec_k,
                            tn=st["n_head"], te=st["eps"],
                            tm=st["moe_top_k"], dn=dn, de=de, dm=dm,
                            top_k=st["top_k"],
                            use_top_p=st["use_top_p"],
                            tp_axis=TP_AXIS, tp_world=self.tp),
            donate=(2, 3, 4, 5))
        return self._dispatch(fn, t_params, d_params, kc, vc, dkc,
                              dvc, toks, pos, live, keys, temps,
                              top_p)

    def paged_decode_step(self, params, pool_k, pool_v, tables, toks,
                          pos, live, keys, temps, top_p, block,
                          kernel="block"):
        from functools import partial

        from .paged import _paged_decode_kernel, _paged_decode_step

        base = (_paged_decode_kernel if kernel == "block"
                else _paged_decode_step)
        # only the block kernel takes the window static (the gather
        # oracle is refused for windowed engines at construction)
        wkw = ({"window": self._window} if kernel == "block" else {})
        fn = self._twin(
            "paged_decode", (block, kernel, self._window),
            lambda: partial(base.__wrapped__,
                            block=block, **self._statics, **wkw,
                            tp_axis=TP_AXIS, tp_world=self.tp),
            donate=(1, 2))
        return self._dispatch(fn, params, pool_k, pool_v, tables,
                              toks, pos, live, keys, temps, top_p)

    def paged_spec_step(self, t_params, d_params, pool_k, pool_v, dkc,
                        dvc, tables, toks, pos, live, keys, temps,
                        top_p, block, kernel="block"):
        from functools import partial

        from .paged import _paged_spec_kernel, _paged_spec_step

        st = self._statics
        spec_k, (dn, de, dm) = self._spec
        base = (_paged_spec_kernel if kernel == "block"
                else _paged_spec_step)
        wkw = ({"window": self._window} if kernel == "block" else {})
        fn = self._twin(
            "paged_spec", (block, kernel, spec_k, dn, de, dm,
                           self._window),
            lambda: partial(base.__wrapped__, block=block,
                            spec_k=spec_k, tn=st["n_head"],
                            te=st["eps"], tm=st["moe_top_k"], dn=dn,
                            de=de, dm=dm, top_k=st["top_k"],
                            use_top_p=st["use_top_p"], **wkw,
                            tp_axis=TP_AXIS, tp_world=self.tp),
            donate=(2, 3, 4, 5))
        return self._dispatch(fn, t_params, d_params, pool_k, pool_v,
                              dkc, dvc, tables, toks, pos, live,
                              keys, temps, top_p)

    def prefill_one(self, params, ids, prompt_len, key, temp, top_p):
        from functools import partial

        from .engine import _prefill_one

        fn = self._twin(
            "prefill_one", (self._window,),
            lambda: partial(_prefill_one.__wrapped__, **self._statics,
                            quant=self._quant, window=self._window,
                            tp_axis=TP_AXIS, tp_world=self.tp))
        return self._dispatch(fn, params, ids, prompt_len, key, temp,
                              top_p)

    def prefill_batch(self, params, ids, plens, seeds, temps, top_p):
        from functools import partial

        from .engine import _prefill_batch

        fn = self._twin(
            "prefill_batch", (self._window,),
            lambda: partial(_prefill_batch.__wrapped__,
                            **self._statics, quant=self._quant,
                            window=self._window,
                            tp_axis=TP_AXIS, tp_world=self.tp))
        return self._dispatch(fn, params, ids, plens, seeds, temps,
                              top_p)

    def chunk_row(self, params, ids, kc_row, vc_row, off):
        from functools import partial

        from .engine import _chunk_row

        ck = self._chunk
        fn = self._twin(
            "chunk_row", tuple(sorted(ck.items())),
            lambda: partial(_chunk_row.__wrapped__, **ck,
                            tp_axis=TP_AXIS, tp_world=self.tp),
            donate=(2, 3))
        return self._dispatch(fn, params, ids, kc_row, vc_row, off)

    def write_slot(self, kc, vc, kc_row, vc_row, slot):
        from .engine import _write_slot

        fn = self._twin("write_slot", (),
                        lambda: _write_slot.__wrapped__,
                        donate=(0, 1))
        return self._dispatch(fn, kc, vc, kc_row, vc_row, slot)

    def read_slot(self, kc, vc, slot):
        from .prefix import _read_slot

        fn = self._twin("read_slot", (),
                        lambda: _read_slot.__wrapped__)
        return self._dispatch(fn, kc, vc, slot)

    def pool_to_row(self, pool_k, pool_v, idx, n_used):
        fn = self._twin("pool_to_row", (), lambda: _pool_to_row_body)
        return self._dispatch(fn, pool_k, pool_v, idx, n_used)

    def row_to_pool(self, pool_k, pool_v, kc_row, vc_row, idx):
        fn = self._twin("row_to_pool", (), lambda: _row_to_pool_body,
                        donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_row, vc_row, idx)

    def rows_to_pool(self, pool_k, pool_v, kc_rows, vc_rows, sel, idx):
        fn = self._twin("rows_to_pool", (),
                        lambda: _rows_to_pool_body, donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_rows, vc_rows,
                              sel, idx)

    def _make_ring_body(self):
        """The ring-prefill twin body: per rank, embed the LOCAL
        sequence chunk, and per layer run causal
        ``ring_self_attention`` over the tp axis (K/V blocks rotate
        the ICI ring; logsumexp-exact partial merges) with the
        REPLICATED weights, dense Megatron-free MLP, and collect the
        chunk's K/V in the GQA-narrow head count.  Returns
        (final-LN hidden, kc, vc) — all sequence-sharded; the
        dispatch wrapper re-places them."""
        import jax.numpy as jnp
        from jax import lax

        from ..models import gpt2_decode as G
        from ..parallel.communicator import _record_collective
        from ..parallel.ring_attention import ring_self_attention

        st = self._statics
        n_head, eps = st["n_head"], st["eps"]
        moe_top_k = st["moe_top_k"]
        tp = self.tp

        def body(params, ids):
            rank = lax.axis_index(TP_AXIS)
            s_loc = ids.shape[1]
            pos = rank * s_loc + jnp.arange(s_loc)
            x = (jnp.take(params["wte"], ids[0], axis=0)[None]
                 + jnp.take(params["wpe"], pos, axis=0)[None])
            ks, vs = [], []
            for p in params["blocks"]:
                h = G._ln(x, p["ln1_s"], p["ln1_b"], eps)
                q = h @ p["wq"] + p["bq"]
                k = h @ p["wk"] + p["bk"]
                v = h @ p["wv"] + p["bv"]
                b, s, e = x.shape
                d = e // n_head
                n_kv = k.shape[-1] // d
                qh = q.reshape(b, s, n_head, d).transpose(0, 2, 1, 3)
                kh = k.reshape(b, s, n_kv, d).transpose(0, 2, 1, 3)
                vh = v.reshape(b, s, n_kv, d).transpose(0, 2, 1, 3)
                krep, vrep = kh, vh
                if n_kv != n_head:
                    # the ring rotates FULL query-head-width K/V (its
                    # per-step kernel has no grouped layout); the
                    # cache keeps the narrow GQA heads below
                    krep = jnp.repeat(kh, n_head // n_kv, axis=1)
                    vrep = jnp.repeat(vh, n_head // n_kv, axis=1)
                # trace-time observe hook: one ring pass issues
                # axis_size ppermutes of this K/V block pair —
                # attributable in Chrome traces like every other
                # collective (axis + world recorded)
                _record_collective("ring_ppermute", [krep, vrep],
                                   axis=TP_AXIS, world=tp)
                a = ring_self_attention(qh, krep, vrep, TP_AXIS,
                                        causal=True, remat=False)
                a = a.transpose(0, 2, 1, 3).reshape(b, s, e)
                x = x + (a @ p["wo"] + p["bo"])
                h2 = G._ln(x, p["ln2_s"], p["ln2_b"], eps)
                x = x + G._mlp(h2, p, moe_top_k)
                ks.append(kh)
                vs.append(vh)
            x = G._ln(x, params["lnf_s"], params["lnf_b"], eps)
            return x, G._cache_stack(ks), G._cache_stack(vs)

        return body

    def ring_prefill_one(self, params, ids, plen, key, temp, top_p):
        """Ring-attention cold admission prefill (the long-context
        round): ``ids`` (1, wn) right-padded at a width divisible by
        both the block size and the mesh width.  One sequence-sharded
        dispatch computes hidden + K/V for the whole prompt — per
        shard the attention tile is O((wn/tp)^2) — then the outputs
        re-place (hidden replicated, rows onto the head-axis cache
        sharding every copy twin expects; one explicit transfer per
        long admission, off the decode hot path) and the admission
        token samples through the same ``_first_from_hidden`` tail
        the chunked path uses.  Token-identical to the serial
        prefill: the logsumexp partial merge reorders the float
        reduction, the same caveat as the decode psum.  Returns the
        ``prefill_one`` contract (tok0, carried key, kc_row,
        vc_row)."""
        import jax.numpy as jnp

        from .engine import _first_from_hidden

        st = self._statics
        fn = self._twin("ring_prefill", (), self._make_ring_body)
        hidden, kc_row, vc_row = self._dispatch(
            fn, self._ring_params, ids)
        hidden = jax.device_put(hidden, self._repl_sh)
        kc_row = jax.tree.map(
            lambda a: jax.device_put(a, self._cache_sh), kc_row)
        vc_row = jax.tree.map(
            lambda a: jax.device_put(a, self._cache_sh), vc_row)
        self._c_ring.inc()
        self.ring_prefills += 1
        tok0, carry_key = _first_from_hidden(
            params, hidden, jnp.int32(plen - 1), key, temp, top_p,
            top_k=st["top_k"], use_top_p=st["use_top_p"])
        return tok0, carry_key, kc_row, vc_row

    # -- lifecycle / reporting -------------------------------------------
    def unregister(self):
        """Release the registry entries (engine close()).  The twin
        cache is module-wide by design — a successor engine with the
        same geometry rides the same compiled executables."""
        self._registry.remove(*self._registered)

    def snapshot(self) -> dict:
        return {
            "shards": self.tp,
            "devices": [str(d) for d in self.mesh.devices.flat],
            "collectives_per_step": 2 * self.n_layer,
            "kv_bytes_per_shard": self._kv_bytes,
            "sharded_dispatches": self._c_dispatch.value,
        }


# -- copy-twin bodies --------------------------------------------------------
# The pool<->row copies take the per-leaf block width off the leaf's
# own shape (paged._leaf_to_row/_leaf_to_pool), so ONE body serves the
# paged arena AND the prefix cache's private pool whatever their block
# sizes — exactly prefix._blocks_to_row/_row_to_blocks' math, restated
# here positionally for the shard_map wrapper.

def _pool_to_row_body(pool_k, pool_v, idx, n_used):
    from .paged import _leaf_to_row

    def gather(pool):
        return _leaf_to_row(pool, idx, n_used, pool.shape[3])

    return jax.tree.map(gather, pool_k), jax.tree.map(gather, pool_v)


def _row_to_pool_body(pool_k, pool_v, kc_row, vc_row, idx):
    from .paged import _leaf_to_pool

    def scatter(pool, row):
        return _leaf_to_pool(pool, row, idx, pool.shape[3])

    return (jax.tree.map(scatter, pool_k, kc_row),
            jax.tree.map(scatter, pool_v, vc_row))


def _rows_to_pool_body(pool_k, pool_v, kc_rows, vc_rows, sel, idx):
    import jax.numpy as jnp

    from .paged import _leaf_to_pool

    def scatter(pool, rows):
        r = jnp.take(rows, sel, axis=1)
        r = jnp.moveaxis(r, 1, 2)
        s = r.shape
        r = r.reshape(s[0], 1, s[1], s[2] * s[3], *s[4:])
        return _leaf_to_pool(pool, r, idx, pool.shape[3])

    return (jax.tree.map(scatter, pool_k, kc_rows),
            jax.tree.map(scatter, pool_v, vc_rows))
