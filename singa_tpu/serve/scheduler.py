"""Iteration-level scheduling policy for the serving engine.

The engine asks the scheduler ONE question per step: "these slots are
free — which queued requests run next?".  Everything the Orca/vLLM
literature calls continuous batching falls out of asking that question
every iteration instead of once per batch: rows retire one by one and
the very same step's schedule() backfills their slots.

Policy here is deliberately simple and exact:

* **FIFO admission** — requests run in arrival order (no reordering,
  so per-request results are reproducible for a given arrival order);
* **prefill/decode interleave** — at most ``max_prefills_per_step``
  admissions per schedule() call, so a burst of arrivals cannot starve
  the decode loop (each prefill is an O(ctx²) forward; each decode
  step is O(ctx)).  Freed-slot backfill beyond the cap waits a step;
* **admission control** — ``enqueue`` rejects at ``max_queue_depth``
  (QueueFullError, synchronous back-pressure), and ``schedule`` drops
  queued requests whose deadline passed (DeadlineExceededError via the
  expired list) BEFORE admitting, so a stale request never occupies a
  slot that a live one could use.

The scheduler owns no device state and never touches jax — it is plain
host code, which is what makes the policy unit-testable with a fake
clock (tests/test_serve.py).
"""

from __future__ import annotations

from collections import deque
from typing import List, Tuple

from ..observe import requests as _reqs
from ..observe.registry import registry as _registry
from .request import GenerationRequest, QueueFullError


class FIFOScheduler:
    """FIFO queue + the admission policy described in the module
    docstring.  ``max_queue_depth``: back-pressure bound (requests, not
    tokens).  ``max_prefills_per_step``: prefill/decode interleave
    knob; None means "fill every free slot immediately"."""

    def __init__(self, max_queue_depth: int = 64,
                 max_prefills_per_step=None):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if max_prefills_per_step is not None \
                and max_prefills_per_step < 1:
            raise ValueError(
                f"max_prefills_per_step must be >= 1 or None, got "
                f"{max_prefills_per_step}")
        self.max_queue_depth = int(max_queue_depth)
        self.max_prefills_per_step = max_prefills_per_step
        self._queue: deque = deque()

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def enqueue(self, request: GenerationRequest):
        if len(self._queue) >= self.max_queue_depth:
            raise QueueFullError(
                f"scheduler queue full (depth {len(self._queue)} of "
                f"max {self.max_queue_depth}); rejecting "
                f"{request.request_id}")
        self._queue.append(request)
        if _reqs._active:
            # request-ledger hook: how many requests sat ahead of this
            # one at enqueue — the queue-wait phase's explanation
            _reqs._ledger.annotate_hop(
                request.request_id,
                queue_depth_at_enqueue=len(self._queue) - 1)

    def drain(self) -> List[GenerationRequest]:
        """Remove and return every queued request (queue order) — the
        supervisor's requeue source after an engine failure."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def requeue_front(self, request: GenerationRequest):
        """Put a popped-but-unadmitted request back at the HEAD of the
        queue (the paged engine's capacity-blocked admission path: the
        request's blocks did not fit this step, so it waits at the
        front — admission order blocks, it never skips).  No depth
        check: the request was already admitted to the queue once."""
        self._queue.appendleft(request)

    def shed_lowest(self, reason, below_priority=None):
        """Load shedding: remove and return the lowest-priority queued
        request (ties: the newest arrival — it has waited least), or
        None when the queue is empty or nothing sits strictly below
        ``below_priority``.  Every shed increments the process-wide
        ``serve.shed_requests{reason=}`` counter."""
        if not self._queue:
            return None
        victim_i = None
        for i, r in enumerate(self._queue):
            p = getattr(r, "priority", 0)
            if victim_i is None \
                    or p <= getattr(self._queue[victim_i], "priority", 0):
                victim_i = i
        victim = self._queue[victim_i]
        if below_priority is not None \
                and getattr(victim, "priority", 0) >= below_priority:
            return None
        del self._queue[victim_i]
        _registry().counter(
            "serve.shed_requests",
            help="queued requests shed by load-shedding admission",
            reason=reason).inc()
        return victim

    def schedule(self, free_slots: int, now: float, cost=None
                 ) -> Tuple[List[GenerationRequest],
                            List[GenerationRequest]]:
        """One scheduling decision: returns ``(admit, expired)``.
        ``admit`` is FIFO order, capped by free_slots and
        max_prefills_per_step; ``expired`` are deadline-passed requests
        removed from the queue (in queue order).  Expiry is checked for
        the WHOLE queue, not just the admissible prefix — a stale
        request deep in the queue should fail fast, not age further
        behind back-pressure.

        ``cost`` (optional): per-request prefill cost the interleave
        budget counts instead of 1 per admission.  The cap exists to
        bound the O(ctx²) prefill work a step can take; a warm
        prefix-cache admission that recomputes at most one block-width
        chunk is priced 0 by the engine, so cached traffic is not
        throttled by the protection built for cold traffic.  FIFO
        order is never violated — a too-expensive head-of-queue
        request STOPS admission for this step rather than being
        skipped."""
        expired = [r for r in self._queue
                   if r.deadline is not None and now > r.deadline]
        if expired:
            dead = {id(r) for r in expired}
            self._queue = deque(r for r in self._queue
                                if id(r) not in dead)
        budget = self.max_prefills_per_step
        admit = []
        spent = 0
        while self._queue and len(admit) < free_slots:
            if budget is not None:
                # cost is only consulted against a finite budget — an
                # uncapped scheduler skips the per-request price probe
                # (a radix lookup per admission) entirely
                c = 1 if cost is None else int(cost(self._queue[0]))
                if spent + c > budget:
                    break
                spent += c
            admit.append(self._queue.popleft())
        return admit, expired


class PriorityScheduler(FIFOScheduler):
    """Strict-priority admission on top of the FIFO machinery: the
    queue is kept ordered by ``GenerationRequest.priority`` (higher
    first), FIFO WITHIN a priority class — so priority-0 traffic
    behaves exactly like the FIFO scheduler until something more
    urgent arrives.  Everything else (deadline expiry, the
    prefill-interleave budget, ``drain``/``shed_lowest``/
    ``requeue_front``, back-pressure) is inherited: ``schedule`` pops
    from the head, and the head is by construction the
    highest-priority oldest request.

    Pairs with the paged engine's preemption (docs/SERVING.md
    "Scheduler policy matrix"): a high-priority arrival that does not
    fit in blocks PREEMPTS strictly-lower-priority live work (swap to
    host, resume later) instead of waiting behind it — SLO pressure
    preempts rather than sheds.  Construct per engine, or pass
    ``scheduler="priority"`` so supervisors and fleets build one per
    replica (an instance forwarded through ``engine_kw`` would be
    shared)."""

    def enqueue(self, request: GenerationRequest):
        if len(self._queue) >= self.max_queue_depth:
            raise QueueFullError(
                f"scheduler queue full (depth {len(self._queue)} of "
                f"max {self.max_queue_depth}); rejecting "
                f"{request.request_id}")
        p = getattr(request, "priority", 0)
        i = len(self._queue)
        while i > 0 and getattr(self._queue[i - 1], "priority", 0) < p:
            i -= 1
        self._queue.insert(i, request)
        if _reqs._active:
            # the request's actual queue position — ahead of every
            # lower-priority request it just overtook
            _reqs._ledger.annotate_hop(request.request_id,
                                       queue_depth_at_enqueue=i)

    def requeue_front(self, request: GenerationRequest):
        """Head of the request's own priority CLASS: ahead of equal
        priorities (it was popped first, so it was oldest), behind
        anything strictly higher that arrived meanwhile."""
        p = getattr(request, "priority", 0)
        i = 0
        while i < len(self._queue) \
                and getattr(self._queue[i], "priority", 0) > p:
            i += 1
        self._queue.insert(i, request)
