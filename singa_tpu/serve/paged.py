"""Block-paged KV arena: ONE memory system for live decode and the
radix prefix cache (the paged-KV round; vLLM/PagedAttention, cited in
ISSUE.md/ROADMAP item 2).

The engine's original memory model reserved worst-case bytes per
request: a ``(L, max_slots, H_kv, max_len, D)`` slot arena where every
slot owns ``max_len`` positions whether its request uses 20 of them or
all of them, plus a SECOND pool for the prefix cache's blocks
(serve/prefix.py).  Short requests therefore wasted most of the arena
and ``max_slots`` capped concurrency far below what the bytes could
carry.  This module collapses both into one pool:

* **block pool** — one preallocated arena of ``num_blocks`` KV blocks
  per K/V, shape ``(L, num_blocks + 1, H_kv, block_size, D)`` (the +1
  is the trash block scatter padding lands in, prefix.py's idiom).
  Leaves are PYTREE-GENERIC: a dense pool is one array per K/V, an
  int8 pool is a ``(values, scales)`` tuple — every copy helper below
  tree-maps with per-leaf rank awareness, which is what lifts the old
  ``int8 + prefix-cache`` refusal;
* **block tables** — a live request's KV is a per-slot block LIST
  grown block-by-block as decode advances.  Capacity is "blocks free",
  not "slots free": a 20-token request holds one block, not a
  ``max_len`` row, so far more requests fit the same bytes;
* **paged pool step** — TWO implementations behind
  ``PagedConfig.kernel``.  The default (``"block"``) is a
  BLOCK-NATIVE online-softmax decode kernel
  (``gpt2_decode.decode_step_paged`` / ``chunk_step_paged``,
  dispatched by ``_paged_decode_kernel`` / ``_paged_spec_kernel``
  below): flash-style attention directly over the pool with the
  block table as the index structure — a ``fori_loop`` over each
  slot's live blocks (bound = the longest LIVE slot's block count,
  one traced scalar), running-max + rescaled-partial-sum
  accumulation, trash and beyond-``pos`` lanes masked, int8
  dequantized per block inside the accumulator; the workspace is
  O(block_size) and the write-back is a read-modify-write of the one
  or two blocks the step touched, so pool bytes still round-trip
  exactly.  ``"gather"`` keeps the original materialize-a-row path
  (``engine._decode_row`` / ``_spec_row`` on a transient
  ``(L, S, H, W, D)`` workspace — bitwise the slot engine's math) as
  the parity oracle: kernel streams are pinned TOKEN-identical to it
  with an allclose logits oracle (online softmax reorders the float
  reduction; tests/test_paged.py).  Either way the PERSISTENT KV
  allocation (what the capacity model and ``bench_serve.py --paged``
  count) is the pool alone;
* **preemption / swap** — a request's blocks can be evicted to HOST
  memory mid-decode (``swap_out``: one fixed-shape gather + device
  sync) and restored later (``swap_in``: one scatter).  The copy is
  byte-exact, so a preempted-and-resumed request's remaining tokens
  are the ones the uninterrupted run would have produced — recompute
  through ``prefill_chunk`` could NOT promise that (decode-step KV
  drifts ~1e-6 from chunked prefill; see serve/prefix.py's
  canonical-KV analysis), which is why resume restores bytes and the
  chunked path is reserved for admissions;
* **unified prefix cache** — with ``prefix_cache=`` on a paged engine
  the radix tree allocates from THIS pool (``PrefixCache(arena=...)``):
  warm admission shares the matched blocks by reference (zero copy),
  retire donation ADOPTS the slot's private prompt blocks into the
  tree (zero copy — ``PrefixCache.adopt_blocks``), and cached-but-
  unreferenced blocks double as soft free space (``alloc`` evicts LRU
  leaves under pressure before failing).

Copy paths (gather/scatter/swap) check the ``serve.paged_copy`` fault
site (singa_tpu.resilience): an injected copy failure fails the engine
TYPED and the supervisor rebuild recovers (bench_chaos.py
``chaos_paged`` gates zero wedged/lost requests under a fault
mid-swap).

Metrics ride the process-wide observe registry as
``serve.paged.{blocks_free,blocks_used,preemptions,swap_in,swap_out}``
with the owning engine's label, and surface in
``health_report()["serve"]["paged"]``.

Compile capture: the paged pool steps dispatch through a small AOT
cache (:func:`_aot_call`) that lowers + compiles each new signature
once, records the XLA cost-analysis table on a ``serve/compile`` trace
span, and registers the tables with ``observe.monitor`` — so paged
executables show up in Chrome traces and crash bundles exactly like
``_GraphRunner`` train steps do (the VERDICT weak-#6 gap: serve-side
``jax.jit`` dispatches used to compile invisibly).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..observe import monitor as _monitor
from ..observe import trace as _trace
from ..observe.registry import registry as _default_registry
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["PagedConfig", "PagedKVArena"]


@dataclass(frozen=True)
class PagedConfig:
    """Knobs for the paged KV arena (hand to
    ``model.serve(paged=...)``; the supervisor/fleet forward it
    verbatim, so every rebuilt replica allocates its own fresh pool).

    ``block_size``: tokens per KV block — the allocation granularity
    AND (when a prefix cache rides the same pool) the reuse
    granularity.  The engine requires ``max_len % block_size == 0``.
    ``num_blocks``: pool capacity in blocks; device memory is
    ``2 * L * num_blocks * H_kv * block_size * D`` elements — compare
    against the slot arena's ``2 * L * max_slots * max_len * H_kv * D``
    to hold the byte budget fixed (docs/SERVING.md "Paged KV").
    ``kernel``: how the pool steps read KV — ``"block"`` (default)
    runs the block-native online-softmax decode kernel
    (``gpt2_decode.decode_step_paged``: O(block_size) workspace,
    attention work proportional to each step's LIVE blocks, trash and
    beyond-``pos`` lanes masked); ``"gather"`` keeps the original
    materialize-a-row path (O(max_len) workspace and attention work —
    bitwise the slot engine's math) as the parity oracle and an
    escape hatch.  Streams are token-identical between the two
    (tests/test_paged.py pins kernel-vs-gather token identity plus an
    allclose logits oracle; online softmax reorders the float
    reduction, so bitwise logit equality is impossible by
    construction).
    ``admit_per_step``: optional ADMISSION INTERLEAVE BUDGET — at
    most this many prefills per scheduling pass (None = unlimited,
    the historical behavior).  A paged engine admits by blocks free,
    so a burst of arrivals otherwise prefills en masse inside one
    step and every live slot's decode TPOT absorbs the stall; a
    small budget (2–3) spreads the same prefill work across steps,
    trading a little TTFT headroom (paged TTFT is ~10-20x below the
    slot arena's to begin with) for flat decode cadence — the
    Sarathi-style chunked-prefill budget in miniature (ROADMAP item
    2a; the request ledger's stall phase is the proof metric).
    ``prefill_token_budget``: the REAL Sarathi-style chunked-prefill
    budget (the long-context round): at most this many prefill
    TOKENS per engine step, and — unlike ``admit_per_step``, which
    only caps how many whole prefills a pass runs — a single
    admission whose prompt exceeds the budget is SPLIT across
    consecutive steps in block-multiple chunks (the engine's
    ``_chunk_row`` / ``gpt2_decode.prefill_chunk`` executables,
    chunk rows pinned bitwise against full prefill), so one 32k
    document admission can never stall the live decode lanes for
    more than one chunk's latency per step.  Must be a multiple of
    ``block_size``; None = off (whole-prompt admissions, the
    historical behavior).  docs/SERVING.md "Long-context serving"
    has the budget-vs-admit_per_step semantics table."""

    block_size: int = 32
    num_blocks: int = 128
    kernel: str = "block"
    admit_per_step: int | None = None
    prefill_token_budget: int | None = None

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 1:
            raise ValueError(
                f"num_blocks must be >= 1, got {self.num_blocks}")
        if self.kernel not in ("block", "gather"):
            raise ValueError(
                f"kernel must be 'block' (block-native online-softmax "
                f"decode) or 'gather' (materialized-row oracle), got "
                f"{self.kernel!r}")
        if self.admit_per_step is not None and self.admit_per_step < 1:
            raise ValueError(
                f"admit_per_step must be >= 1 (or None for "
                f"unlimited), got {self.admit_per_step}")
        if self.prefill_token_budget is not None:
            if self.prefill_token_budget < self.block_size \
                    or self.prefill_token_budget % self.block_size:
                raise ValueError(
                    f"prefill_token_budget "
                    f"({self.prefill_token_budget}) must be a "
                    f"positive multiple of block_size "
                    f"({self.block_size}): chunked prefill advances "
                    f"in block-width windows")


# -- pytree-generic fixed-shape copies ---------------------------------------
# The generalization of serve/prefix.py's _blocks_to_row/_row_to_blocks:
# identical math on dense (L, N+1, H, B, D) leaves, and the same
# moveaxis/reshape on the trailing-axis-free (L, N+1, H, B) scales leaf
# of an int8 pool — which is what makes quantized pools first-class
# (the old int8 + prefix-cache refusal existed because these copies
# were dense-only).  Shapes are keyed on (pool, row) geometry only, so
# each compiles once per engine geometry and serves any chain length
# (the index vector is always the full row's worth of lanes, unused
# lanes masked / pointed at the trash block).

def _leaf_to_row(pool, idx, n_used, block):
    """One leaf's gather: (L, N+1, H, B, ...) pool -> (L, 1, H, W, ...)
    row, lanes >= n_used zeroed (junk the chunked prefill and the
    decode position mask never read live)."""
    b = jnp.take(pool, idx, axis=1)              # (L, nb, H, B, ...)
    b = jnp.moveaxis(b, 1, 2)                    # (L, H, nb, B, ...)
    s = b.shape
    row = b.reshape(s[0], s[1], s[2] * s[3], *s[4:])
    live = (jnp.arange(s[2] * s[3]) < n_used * block)
    live = live.reshape((1, 1, -1) + (1,) * (row.ndim - 3))
    return jnp.where(live, row, 0)[:, None]      # (L, 1, H, W, ...)


def _leaf_to_pool(pool, row, idx, block):
    """One leaf's scatter: row lanes -> pool blocks at ``idx`` (lanes
    that should not store anything point at the trash block)."""
    r = row[:, 0]                                # (L, H, W, ...)
    s = r.shape
    b = r.reshape(s[0], s[1], idx.shape[0], block, *s[3:])
    b = jnp.moveaxis(b, 2, 1)                    # (L, nb, H, B, ...)
    return pool.at[:, idx].set(b)


@partial(jax.jit, static_argnames=("block",))
def _pool_to_row(pool_k, pool_v, idx, n_used, block):
    """Gather ``idx`` (nb,) pool blocks into fresh (L, 1, H, W, ...)
    cache rows, tree-mapped over dense or (values, scales) pools."""
    g = partial(_leaf_to_row, idx=idx, n_used=n_used, block=block)
    return jax.tree.map(g, pool_k), jax.tree.map(g, pool_v)


@partial(jax.jit, static_argnames=("block",), donate_argnums=(0, 1))
def _row_to_pool(pool_k, pool_v, kc_row, vc_row, idx, block):
    """Scatter cache-row lanes into the pool at ``idx``; pools DONATED
    (the caller rebinds) so a donation/swap is a scatter in place, not
    an O(pool) copy."""
    s = partial(_leaf_to_pool, idx=idx, block=block)
    return (jax.tree.map(lambda p, r: s(p, r), pool_k, kc_row),
            jax.tree.map(lambda p, r: s(p, r), pool_v, vc_row))


@partial(jax.jit, donate_argnums=(0, 1))
def _copy_pool_block(pool_k, pool_v, src, dst):
    """Copy ONE block's bytes ``src`` -> ``dst`` inside the pool (both
    traced ints — one executable per engine geometry).  The
    copy-on-first-write path of KV forking: a forked branch about to
    write into a block a sibling still references gets its own byte
    copy first, so siblings never observe each other's writes."""
    cp = lambda p: p.at[:, dst].set(p[:, src])
    return jax.tree.map(cp, pool_k), jax.tree.map(cp, pool_v)


@partial(jax.jit, static_argnames=("block",), donate_argnums=(0, 1))
def _rows_to_pool(pool_k, pool_v, kc_rows, vc_rows, sel, idx, block):
    """Batched admission scatter (the gather-tax round): rows
    (L, R, H, W, ...) from ONE batched pass prefill, ``sel`` (R',)
    the successfully-admitted row indices, ``idx`` (R' * W//B,) the
    flattened per-row block targets (trash for unmapped lanes) — ONE
    donated scatter writes every admission of a scheduling pass, so
    K admissions stop costing the live decode lanes K dispatches."""
    def leaf(pool, rows):
        r = jnp.take(rows, sel, axis=1)          # (L, R', H, W, ...)
        r = jnp.moveaxis(r, 1, 2)                # (L, H, R', W, ...)
        s = r.shape
        r = r.reshape(s[0], 1, s[1], s[2] * s[3], *s[4:])
        return _leaf_to_pool(pool, r, idx, block)

    return (jax.tree.map(lambda p, r: leaf(p, r), pool_k, kc_rows),
            jax.tree.map(lambda p, r: leaf(p, r), pool_v, vc_rows))


def _gather_leaf(pool, tbl):
    """In-step row gather (no batch axis, no zero mask — the decode
    position mask covers everything past ``pos``, and every position
    <= pos lives in an allocated block by the engine's growth
    invariant)."""
    b = jnp.take(pool, tbl, axis=1)
    b = jnp.moveaxis(b, 1, 2)
    s = b.shape
    return b.reshape(s[0], s[1], s[2] * s[3], *s[4:])


def _slice_block(leaf, off, block):
    """The (L, H, B, ...) block at position offset ``off`` (traced) of
    one slot's (L, H, W, ...) cache leaf."""
    start = (0, 0, off) + (0,) * (leaf.ndim - 3)
    sizes = (leaf.shape[0], leaf.shape[1], block) + leaf.shape[3:]
    return jax.lax.dynamic_slice(leaf, start, sizes)


# -- paged pool steps --------------------------------------------------------
# The per-row math is engine._decode_row/_spec_row — the SAME functions
# the slot-arena steps vmap — so the paged engine's logits are bitwise
# the slot engine's (the gathered row equals the slot row at every
# position <= pos: blocks round-trip as byte copies, and positions
# beyond pos are masked before they can contribute).  Imported lazily
# at call time to avoid a module cycle (engine imports this module for
# the arena class).

@partial(jax.jit,
         static_argnames=("block", "n_head", "eps", "moe_top_k",
                          "top_k", "use_top_p", "tp_axis", "tp_world",
                          "with_lp"),
         donate_argnums=(1, 2))
def _paged_decode_step(params, pool_k, pool_v, tables, toks, pos, live,
                       keys, temps, top_p, masks=None, block=None,
                       n_head=None, eps=None, moe_top_k=None,
                       top_k=None, use_top_p=None, tp_axis=None,
                       tp_world=1, with_lp=False):
    """Advance EVERY slot one token against the block pool: tables
    (S, W//B) int32 block ids (trash-padded), pools donated.  Per slot:
    gather its blocks into a row, run the shared decode-row math, then
    scatter ONLY the block containing ``pos`` back (one written block
    per slot per step; dead slots write the trash block).  Returns
    (next_toks, pool_k, pool_v, new_keys) — plus a (S,) chosen-token
    logprob vector when ``with_lp`` (static; the fork round's
    best-of-n ranking signal).  ``masks`` is None (legacy math,
    bitwise unchanged) or a (S, V) bool vocab-mask batch (constrained
    decoding — False lanes are NEG_INF'd before the shared sample
    chain; an all-True row is a bitwise no-op)."""
    from .engine import _decode_row

    trash = jax.tree.leaves(pool_k)[0].shape[1] - 1

    def row(tbl, tok, pos_r, live_r, key, temp, mask_r):
        kc_r = jax.tree.map(lambda p: _gather_leaf(p, tbl), pool_k)
        vc_r = jax.tree.map(lambda p: _gather_leaf(p, tbl), pool_v)
        res = _decode_row(
            params, kc_r, vc_r, tok, pos_r, live_r, key, temp, top_p,
            n_head, eps, moe_top_k, top_k, use_top_p,
            tp_axis=tp_axis, tp_world=tp_world, mask=mask_r,
            with_lp=with_lp)
        nxt, kc2, vc2, k2 = res[:4]
        lp = res[4] if with_lp else jnp.float32(0.0)
        p_c = jnp.where(live_r, pos_r, 0)
        blk = p_c // block
        off = blk * block
        kb = jax.tree.map(lambda a: _slice_block(a, off, block), kc2)
        vb = jax.tree.map(lambda a: _slice_block(a, off, block), vc2)
        dst = jnp.where(live_r, tbl[blk], trash)
        return nxt, kb, vb, dst, k2, lp

    m_ax = None if masks is None else 0
    nxt, kb, vb, dst, keys2, lps = jax.vmap(
        row, in_axes=(0, 0, 0, 0, 0, 0, m_ax),
        out_axes=(0, 1, 1, 0, 0, 0))(tables, toks, pos, live, keys,
                                     temps, masks)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst].set(b), pool_k, kb)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst].set(b), pool_v, vb)
    if with_lp:
        return nxt, pool_k, pool_v, keys2, lps
    return nxt, pool_k, pool_v, keys2


@partial(jax.jit,
         static_argnames=("block", "spec_k", "tn", "te", "tm", "dn",
                          "de", "dm", "top_k", "use_top_p", "tp_axis",
                          "tp_world"),
         donate_argnums=(2, 3, 4, 5))
def _paged_spec_step(t_params, d_params, pool_k, pool_v, dkc, dvc,
                     tables, toks, pos, live, keys, temps, top_p,
                     block, spec_k, tn, te, tm, dn, de, dm, top_k,
                     use_top_p, tp_axis=None, tp_world=1):
    """Speculative chunk against the block pool: the TARGET cache is
    paged (gather row -> shared spec-row math -> scatter back the one
    or two blocks the verify chunk wrote — ``spec_k <= block_size`` is
    validated at engine construction so a chunk never spans more than
    two); the DRAFT arena stays slot-shaped (donated, advanced in
    lockstep — it is small by construction and carries no prefix
    cache).  Returns (out, a_draft, pool_k, pool_v, dkc, dvc,
    new_keys)."""
    from .engine import _spec_row

    trash = jax.tree.leaves(pool_k)[0].shape[1] - 1

    def row(dkc_r, dvc_r, tbl, tok, pos_r, live_r, key, temp):
        kc_r = jax.tree.map(lambda p: _gather_leaf(p, tbl), pool_k)
        vc_r = jax.tree.map(lambda p: _gather_leaf(p, tbl), pool_v)
        out, a_draft, kc2, vc2, dkc2, dvc2, k2 = _spec_row(
            t_params, d_params, kc_r, vc_r, dkc_r, dvc_r, tok, pos_r,
            live_r, key, temp, top_p, spec_k, tn, te, tm, dn, de, dm,
            top_k, use_top_p, tp_axis=tp_axis, tp_world=tp_world)
        p_c = jnp.where(live_r, pos_r, 0)
        b0 = p_c // block
        b1 = (p_c + spec_k - 1) // block
        kb0 = jax.tree.map(
            lambda a: _slice_block(a, b0 * block, block), kc2)
        vb0 = jax.tree.map(
            lambda a: _slice_block(a, b0 * block, block), vc2)
        kb1 = jax.tree.map(
            lambda a: _slice_block(a, b1 * block, block), kc2)
        vb1 = jax.tree.map(
            lambda a: _slice_block(a, b1 * block, block), vc2)
        dst0 = jnp.where(live_r, tbl[b0], trash)
        # same-block chunks route the second write to trash so the two
        # scatters never collide on a real block
        dst1 = jnp.where(live_r & (b1 > b0), tbl[b1], trash)
        return (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1, dkc2,
                dvc2, k2)

    (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1, dkc, dvc,
     keys2) = jax.vmap(
        row, in_axes=(1, 1, 0, 0, 0, 0, 0, 0),
        out_axes=(0, 0, 1, 1, 0, 1, 1, 0, 1, 1, 0))(
        dkc, dvc, tables, toks, pos, live, keys, temps)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst0].set(b), pool_k, kb0)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst0].set(b), pool_v, vb0)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst1].set(b), pool_k, kb1)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst1].set(b), pool_v, vb1)
    return out, a_draft, pool_k, pool_v, dkc, dvc, keys2


# -- block-native pool steps (the gather-tax round) --------------------------
# Same signatures and scatter-back write path as the gather steps
# above, but the per-row math is engine._decode_row_paged /
# _spec_row_paged: flash-style online-softmax attention DIRECTLY over
# the (L, N+1, H_kv, B, D) pool with the block table as the index
# structure — a fori_loop over each slot's live blocks, O(block_size)
# workspace, no materialized (max_len) row.  The loop bound is the
# MAX live-block count across the pool (one traced scalar, so one
# executable serves every step and work scales with the longest LIVE
# slot, not with max_len).  Host-side block accounting, growth,
# preemption/swap, and the prefix cache are untouched — they see the
# same (tables, pools, written blocks) contract.

@partial(jax.jit,
         static_argnames=("block", "n_head", "eps", "moe_top_k",
                          "top_k", "use_top_p", "window", "tp_axis",
                          "tp_world", "with_lp"),
         donate_argnums=(1, 2))
def _paged_decode_kernel(params, pool_k, pool_v, tables, toks, pos,
                         live, keys, temps, top_p, masks=None,
                         block=None, n_head=None, eps=None,
                         moe_top_k=None, top_k=None, use_top_p=None,
                         window=None, tp_axis=None, tp_world=1,
                         with_lp=False):
    """Advance EVERY slot one token against the block pool WITHOUT
    gathering rows: per slot, online-softmax attention over its live
    blocks (beyond-``pos`` and trash lanes masked) plus the step's
    own K/V as the current lane, then scatter back ONLY the
    read-modified block containing ``pos`` (dead slots write the
    trash block).  Returns (next_toks, pool_k, pool_v, new_keys) —
    the same contract as :func:`_paged_decode_step`.

    ``window`` (static): sliding-window decode (the long-context
    round) — each slot's query additionally masks pool lanes at
    positions <= pos - window, and the block loop STARTS at the
    lowest in-window block across live slots, so a windowed long
    chat's attention work is O(window) blocks regardless of how far
    ``pos`` has advanced (the engine drops fully-out-of-window
    blocks back to the free list host-side; their table entries are
    trash by then, so the bound is a work optimization, never a
    correctness input)."""
    from .engine import _decode_row_paged

    trash = jax.tree.leaves(pool_k)[0].shape[1] - 1
    p_all = jnp.where(live, pos, 0)
    n_blk = jnp.max((p_all + block - 1) // block)
    blk_lo = None
    if window is not None:
        lo = jnp.maximum(0, (p_all - window + 1) // block)
        blk_lo = jnp.min(jnp.where(live, lo, n_blk))

    def row(tbl, tok, pos_r, live_r, key, temp, mask_r):
        res = _decode_row_paged(
            params, pool_k, pool_v, tbl, tok, pos_r, live_r, key,
            temp, top_p, n_blk, block, trash, n_head, eps, moe_top_k,
            top_k, use_top_p, window=window, blk_lo=blk_lo,
            tp_axis=tp_axis, tp_world=tp_world, mask=mask_r,
            with_lp=with_lp)
        nxt, kb, vb, k2 = res[:4]
        lp = res[4] if with_lp else jnp.float32(0.0)
        p_c = jnp.where(live_r, pos_r, 0)
        dst = jnp.where(live_r, tbl[p_c // block], trash)
        return nxt, kb, vb, dst, k2, lp

    m_ax = None if masks is None else 0
    nxt, kb, vb, dst, keys2, lps = jax.vmap(
        row, in_axes=(0, 0, 0, 0, 0, 0, m_ax),
        out_axes=(0, 1, 1, 0, 0, 0))(tables, toks, pos, live, keys,
                                     temps, masks)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst].set(b), pool_k, kb)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst].set(b), pool_v, vb)
    if with_lp:
        return nxt, pool_k, pool_v, keys2, lps
    return nxt, pool_k, pool_v, keys2


@partial(jax.jit,
         static_argnames=("block", "spec_k", "tn", "te", "tm", "dn",
                          "de", "dm", "top_k", "use_top_p", "window",
                          "tp_axis", "tp_world"),
         donate_argnums=(2, 3, 4, 5))
def _paged_spec_kernel(t_params, d_params, pool_k, pool_v, dkc, dvc,
                       tables, toks, pos, live, keys, temps, top_p,
                       block, spec_k, tn, te, tm, dn, de, dm, top_k,
                       use_top_p, window=None, tp_axis=None,
                       tp_world=1):
    """Speculative chunk against the block pool, block-natively: the
    draft scan and verify are the gather step's (shared helpers in
    engine.py), the TARGET chunk attends the pool through the
    chunk-query online-softmax accumulator, and the write-back
    splits each slot's returned DOUBLE block into the one or two
    blocks the chunk spans (same dst0/dst1 trash-routing as the
    gather step — ``spec_k <= block_size`` is validated at engine
    construction).  Returns (out, a_draft, pool_k, pool_v, dkc, dvc,
    new_keys)."""
    from .engine import _spec_row_paged

    trash = jax.tree.leaves(pool_k)[0].shape[1] - 1
    p_all = jnp.where(live, pos, 0)
    n_blk = jnp.max((p_all + block - 1) // block)
    blk_lo = None
    if window is not None:
        # the LOWEST query of a verify chunk is position pos itself,
        # so the same bound as the decode kernel's covers every query
        lo = jnp.maximum(0, (p_all - window + 1) // block)
        blk_lo = jnp.min(jnp.where(live, lo, n_blk))

    def row(dkc_r, dvc_r, tbl, tok, pos_r, live_r, key, temp):
        out, a_draft, kdbl, vdbl, dkc2, dvc2, k2 = _spec_row_paged(
            t_params, d_params, pool_k, pool_v, dkc_r, dvc_r, tbl,
            tok, pos_r, live_r, key, temp, top_p, n_blk, spec_k,
            block, trash, tn, te, tm, dn, de, dm, top_k, use_top_p,
            window=window, blk_lo=blk_lo,
            tp_axis=tp_axis, tp_world=tp_world)
        p_c = jnp.where(live_r, pos_r, 0)
        b0 = p_c // block
        b1 = (p_c + spec_k - 1) // block
        kb0 = jax.tree.map(lambda a: a[:, :, :block], kdbl)
        vb0 = jax.tree.map(lambda a: a[:, :, :block], vdbl)
        kb1 = jax.tree.map(lambda a: a[:, :, block:], kdbl)
        vb1 = jax.tree.map(lambda a: a[:, :, block:], vdbl)
        dst0 = jnp.where(live_r, tbl[b0], trash)
        # same-block chunks route the second write to trash so the two
        # scatters never collide on a real block
        dst1 = jnp.where(live_r & (b1 > b0), tbl[b1], trash)
        return (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1, dkc2,
                dvc2, k2)

    (out, a_draft, kb0, vb0, dst0, kb1, vb1, dst1, dkc, dvc,
     keys2) = jax.vmap(
        row, in_axes=(1, 1, 0, 0, 0, 0, 0, 0),
        out_axes=(0, 0, 1, 1, 0, 1, 1, 0, 1, 1, 0))(
        dkc, dvc, tables, toks, pos, live, keys, temps)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst0].set(b), pool_k, kb0)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst0].set(b), pool_v, vb0)
    pool_k = jax.tree.map(lambda p, b: p.at[:, dst1].set(b), pool_k, kb1)
    pool_v = jax.tree.map(lambda p, b: p.at[:, dst1].set(b), pool_v, vb1)
    return out, a_draft, pool_k, pool_v, dkc, dvc, keys2


# -- AOT compile capture (VERDICT weak #6) -----------------------------------
# Serve-side executables used to compile invisibly: no span, no cost
# table, nothing in crash bundles.  The paged steps dispatch through
# this cache instead — each new (function, shapes, statics) signature
# is lowered + compiled ONCE under a serve/compile span carrying the
# XLA cost-analysis scalars, and the tables feed monitor crash bundles
# through the registered cost source below.  Falls back to the plain
# jit dispatch if AOT lowering is unavailable.

_MISS = object()
_aot_cache = {}          # (name, leaf shapes/dtypes, statics) -> Compiled|None
_aot_costs = []          # [{"key": ..., "cost": {...}}] for crash bundles


def _paged_cost_tables():
    return list(_aot_costs)


_monitor.register_cost_source(_paged_cost_tables)


def _cost_scalars(cost):
    try:
        from ..model import _cost_args
        return _cost_args(cost)
    except Exception:
        return {}


def _aot_call(name, fn, *args, _memo=None, _token=None, **statics):
    """Dispatch ``fn(*args, **statics)`` through the AOT cache.  The
    compiled executable takes only the traced args (statics were
    consumed at lowering); the cache key mirrors jit's (leaf shapes +
    dtypes + statics), so warm/timed engines, supervisor rebuilds, and
    fleet replicas with identical geometry all share one compile —
    the same restart-is-a-cache-hit contract the jitted paths keep.
    ``_memo``/``_token``: optional caller-owned signature memo — an
    engine's dispatch shapes are FIXED per (step, batch width), so
    the executor caches the expensive leaf-shape key under a cheap
    token instead of re-walking ~80 param leaves every decode step
    (a measurable host tax on the per-step path)."""
    key = _memo.get(_token) if _memo is not None else None
    if key is None:
        key = (name,
               tuple((tuple(a.shape), str(a.dtype))
                     for a in jax.tree.leaves(args)),
               tuple(sorted(statics.items())))
        if _memo is not None:
            _memo[_token] = key
    entry = _aot_cache.get(key, _MISS)
    if entry is _MISS:
        with _trace.span("serve/compile", cat="serve", fn=name) as sp:
            try:
                compiled = fn.lower(*args, **statics).compile()
                scalars = _cost_scalars(compiled.cost_analysis())
                _aot_costs.append(
                    {"key": f"serve.paged/{name}", "cost": scalars})
                sp.set(**scalars)
                entry = compiled
            except Exception:
                entry = None  # no AOT on this backend: plain jit path
        _aot_cache[key] = entry
    if entry is not None:
        return entry(*args)
    return fn(*args, **statics)


def _compile_cache_size():
    """Entries in the paged AOT cache — counted alongside the jitted
    functions' ``_cache_size()`` by ``bench_serve._serve_jit_cache_size``
    so the no-runtime-recompiles pin covers the paged dispatch path
    too."""
    return len(_aot_cache)


# -- the arena ---------------------------------------------------------------

class PagedKVArena:
    """Host-side owner of the block pool: free list, block accounting,
    the copy entry points the engine drives, swap buffers, and
    metrics.  Allocation is block-granular, so there is no external
    fragmentation by construction — any ``n`` free blocks satisfy any
    ``n``-block request (tests/test_paged.py churn-checks the
    accounting invariant ``free + used == num_blocks`` with cached
    blocks counted in ``used``)."""

    def __init__(self, config, n_layer, n_kv_head, head_dim, dtype,
                 row_width, quant=False, engine_label="0", reg=None,
                 tp=None):
        self.config = config
        B, N = config.block_size, config.num_blocks
        self.block_size = B
        self.num_blocks = N
        self.trash = N
        if row_width % B != 0:
            raise ValueError(
                f"row width ({row_width}) must be a multiple of "
                f"block_size ({B})")
        self.row_blocks = row_width // B
        self.quant = bool(quant)
        # tensor-parallel executor (serve/tp.py): the pool leaves are
        # placed SHARDED over the tp mesh's H_kv axis (each shard owns
        # a (L, N+1, H_kv/tp, B, D) slice + its scales slice) and the
        # gather/scatter/swap copies dispatch through the executor's
        # sharded twins.  Host-side block accounting is untouched —
        # block ids are the same on every shard
        self._tp = tp

        def pool(shape_tail):
            if quant:
                z = (jnp.zeros((n_layer, N + 1, n_kv_head, B)
                               + shape_tail, jnp.int8),
                     jnp.zeros((n_layer, N + 1, n_kv_head, B),
                               jnp.float32))
            else:
                z = jnp.zeros((n_layer, N + 1, n_kv_head, B)
                              + shape_tail, dtype)
            return z if tp is None else tp.place_cache(z)

        self.pool_k = pool((head_dim,))
        self.pool_v = pool((head_dim,))
        self._free = list(range(N))
        # LIVE-slot reference counts (the fork round): a block a forked
        # branch shares with its siblings carries an entry here (count
        # >= 2; allocated-but-unshared blocks have an implicit count of
        # 1 and no entry).  ``free`` decrements and only returns a
        # block to the free list at count 1 — existing callers see the
        # historical free() exactly when nothing is forked.  Disjoint
        # from the prefix tree's node refs by construction: tree-owned
        # (cached) blocks are never arena-shared, live tails are never
        # tree-owned until retire adoption (which is capped below the
        # first shared block by the engine).
        self._refs = {}
        # soft free space: the engine wires this to the prefix cache's
        # LRU leaf eviction so cached-but-unreferenced blocks are
        # reclaimed before an allocation fails
        self.evict_cb = None
        self._log = get_channel("serve")
        reg = reg if reg is not None else _default_registry()
        lbl = dict(engine=engine_label)
        self._g_free = reg.gauge(
            "serve.paged.blocks_free",
            help="pool blocks on the free list", **lbl)
        self._g_used = reg.gauge(
            "serve.paged.blocks_used",
            help="pool blocks held by live slots or the prefix cache "
                 "(a swapped-out request holds NONE — its blocks were "
                 "freed at preemption and resume re-allocates its "
                 "full need)", **lbl)
        self._c_preempt = reg.counter(
            "serve.paged.preemptions",
            help="live requests preempted (blocks evicted to host)",
            **lbl)
        self._c_swap_out = reg.counter(
            "serve.paged.swap_out",
            help="request KV rows copied device -> host", **lbl)
        self._c_swap_in = reg.counter(
            "serve.paged.swap_in",
            help="request KV rows restored host -> device", **lbl)
        self._c_window_drop = reg.counter(
            "serve.paged.window_drops",
            help="out-of-window blocks a sliding-window slot dropped "
                 "back to the free list as its position advanced "
                 "(the O(window) memory model's reclaim path)", **lbl)
        self.window_drops = 0
        self._registered = [self._g_free, self._g_used, self._c_preempt,
                            self._c_swap_out, self._c_swap_in,
                            self._c_window_drop]
        self._registry = reg
        self._update_gauges()

    # -- accounting ------------------------------------------------------
    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_used(self) -> int:
        return self.num_blocks - len(self._free)

    def _update_gauges(self):
        self._g_free.set(self.blocks_free)
        self._g_used.set(self.blocks_used)

    def alloc(self, n) -> list | None:
        """``n`` pool blocks, or None — all or nothing, so a partial
        grab can never strand a request mid-allocation.  Under
        pressure the prefix cache's LRU leaves are evicted first
        (``evict_cb``); evicted blocks stay freed even when the
        request ultimately does not fit."""
        while len(self._free) < n and self.evict_cb is not None:
            blk = self.evict_cb()
            if blk is None:
                break
            self._free.append(blk)
        if len(self._free) < n:
            self._update_gauges()
            return None
        out = [self._free.pop() for _ in range(n)]
        self._update_gauges()
        return out

    def free(self, blocks):
        """Release ``blocks``: a block no live reference still shares
        returns to the free list; a SHARED block (a forked sibling
        still holds it) just sheds one reference — bytes stay put
        until the last holder frees it.  With no forks in flight this
        is exactly the historical extend-the-free-list."""
        if not self._refs:
            self._free.extend(blocks)
            self._update_gauges()
            return
        for b in blocks:
            c = self._refs.get(b)
            if c is None:
                self._free.append(b)
            elif c <= 2:
                del self._refs[b]
            else:
                self._refs[b] = c - 1
        self._update_gauges()

    # -- live-slot sharing (the fork round) ------------------------------
    @property
    def shared_blocks(self) -> int:
        """Blocks currently referenced by MORE than one live slot."""
        return len(self._refs)

    def share(self, blocks):
        """Add one live reference to each of ``blocks`` (a fork's
        block-table copy: the child's table points at the parent's
        blocks; nothing moves on device)."""
        for b in blocks:
            self._refs[b] = self._refs.get(b, 1) + 1

    def is_shared(self, block) -> bool:
        return block in self._refs

    def ref_count(self, block) -> int:
        return self._refs.get(block, 1)

    def copy_block(self, src, dst):
        """Copy ``src``'s bytes into ``dst`` — the copy-on-first-write
        of a forked branch about to write into a block a sibling still
        references.  Checks the ``serve.fork_copy`` fault site (the
        chaos_fork scenario's injection point: a raising copy rejects
        ONLY the writing branch; siblings keep their intact bytes)."""
        if _faults._armed:
            _faults.check("serve.fork_copy")
        if self._tp is not None:
            # fork is typed-rejected on sharded executors at submit;
            # reaching here means a caller bypassed validation
            raise RuntimeError(
                "copy_block on a tensor-parallel pool: KV forking "
                "requires the default executor")
        self.pool_k, self.pool_v = _copy_pool_block(
            self.pool_k, self.pool_v, jnp.int32(src), jnp.int32(dst))

    # -- device copies ---------------------------------------------------
    def _pad_idx(self, blocks):
        idx = np.full(self.row_blocks, self.trash, np.int32)
        idx[:len(blocks)] = blocks
        return jnp.asarray(idx)

    def gather_row(self, blocks, n_used=None):
        """Fixed-shape row holding ``blocks``' contents at lanes
        [0, len(blocks)); lanes >= ``n_used`` (default: all of them)
        zeroed.  One executable for every chain length."""
        if _faults._armed:
            _faults.check("serve.paged_copy")
        n = len(blocks) if n_used is None else n_used
        if self._tp is not None:
            return self._tp.pool_to_row(self.pool_k, self.pool_v,
                                        self._pad_idx(blocks),
                                        jnp.int32(n))
        return _pool_to_row(self.pool_k, self.pool_v,
                            self._pad_idx(blocks), jnp.int32(n),
                            block=self.block_size)

    def scatter_row(self, kc_row, vc_row, lanes):
        """Write row lanes into pool blocks: ``lanes`` maps lane index
        -> block id; unmapped lanes point at the trash block.  One
        donated scatter — the pool updates in place.  The lane count
        comes off the ROW's own width, so NARROW rows (the paged
        cold-admission fast path prefills at the smallest
        block-multiple width covering the prompt, not max_len) scatter
        through the same entry point."""
        if _faults._armed:
            _faults.check("serve.paged_copy")
        row_w = jax.tree.leaves(kc_row)[0].shape[3]
        idx = np.full(row_w // self.block_size, self.trash, np.int32)
        for lane, blk in lanes.items():
            idx[lane] = blk
        if self._tp is not None:
            self.pool_k, self.pool_v = self._tp.row_to_pool(
                self.pool_k, self.pool_v, kc_row, vc_row,
                jnp.asarray(idx))
            return
        self.pool_k, self.pool_v = _row_to_pool(
            self.pool_k, self.pool_v, kc_row, vc_row,
            jnp.asarray(idx), block=self.block_size)

    def scatter_rows(self, kc_rows, vc_rows, sel, lanes_list):
        """Batched admission scatter: ``kc_rows``/``vc_rows`` the
        (L, R, H, W, ...) stacked rows of one pass prefill, ``sel``
        the admitted row indices, ``lanes_list`` one lane->block dict
        per selected row.  ONE device dispatch for the whole pass
        (``_rows_to_pool``); one ``serve.paged_copy`` policy tick —
        one logical admission write."""
        if _faults._armed:
            _faults.check("serve.paged_copy")
        row_w = jax.tree.leaves(kc_rows)[0].shape[3]
        nb = row_w // self.block_size
        idx = np.full(len(sel) * nb, self.trash, np.int32)
        for r, lanes in enumerate(lanes_list):
            for lane, blk in lanes.items():
                idx[r * nb + lane] = blk
        if self._tp is not None:
            self.pool_k, self.pool_v = self._tp.rows_to_pool(
                self.pool_k, self.pool_v, kc_rows, vc_rows,
                jnp.asarray(np.asarray(sel, np.int32)),
                jnp.asarray(idx))
            return
        self.pool_k, self.pool_v = _rows_to_pool(
            self.pool_k, self.pool_v, kc_rows, vc_rows,
            jnp.asarray(np.asarray(sel, np.int32)), jnp.asarray(idx),
            block=self.block_size)

    # -- swap / ship images ----------------------------------------------
    # Both host-image paths — preemption swap AND fleet KV shipping —
    # produce/consume the SAME versioned serve/kvimage.py format, so
    # the two cannot drift and a truncated or geometry-mismatched
    # image fails typed before any scatter touches the pool.

    def swap_out(self, blocks, n_data) -> "KVImage":
        """Copy ``blocks``' first ``n_data`` lanes to HOST memory (one
        gather + device sync) — the preemption path.  Returns a
        full-row-width :class:`~singa_tpu.serve.kvimage.KVImage` (one
        gather executable per engine geometry, the historical swap
        shape)."""
        from .kvimage import pack_image

        kc_row, vc_row = self.gather_row(blocks, n_used=n_data)
        self._c_swap_out.inc()
        return pack_image(jax.tree.map(np.asarray, kc_row),
                          jax.tree.map(np.asarray, vc_row),
                          block_size=self.block_size, n_data=n_data,
                          quant=self.quant)

    def swap_in(self, image, blocks):
        """Restore a swapped-out image's lanes into freshly allocated
        ``blocks`` (one scatter — ``scatter_row`` carries the
        ``serve.paged_copy`` fault check, so one logical restore is
        one policy tick).  The image validates against THIS pool's
        geometry first (:class:`~singa_tpu.serve.kvimage.KVImageError`
        on any mismatch — never scatters garbage).  Byte-exact: the
        resumed request's cache state is exactly what swap_out
        saved."""
        image.validate(self.block_size, self.quant,
                       pool_k=self.pool_k)
        self._c_swap_in.inc()
        self.scatter_row(jax.tree.map(jnp.asarray, image.kc),
                         jax.tree.map(jnp.asarray, image.vc),
                         {j: b for j, b in enumerate(blocks)})

    def export_image(self, blocks, n_data) -> "KVImage":
        """Gather ``blocks``' first ``n_data`` lanes into a NARROW
        host image (``n_data * block_size`` lanes — ship bytes track
        the shipped prefix, not ``max_len``): the KV-shipping source
        path.  Packs directly (NOT via :meth:`swap_out` — the
        ``serve.paged.swap_out`` counter means preemption pressure
        and must not absorb ship traffic).  Checks the
        ``serve.kv_ship`` fault site — an injected mid-ship failure
        raises typed and the fleet requeues the request
        cold-but-correct."""
        from .kvimage import pack_image

        if _faults._armed:
            _faults.check("serve.kv_ship")
        kc_row, vc_row = self.gather_row(blocks, n_used=n_data)
        img = pack_image(jax.tree.map(np.asarray, kc_row),
                         jax.tree.map(np.asarray, vc_row),
                         block_size=self.block_size, n_data=n_data,
                         quant=self.quant)
        return img.narrowed()

    def export_row_image(self, kc_row, vc_row, n_data) -> "KVImage":
        """Build a narrow ship image straight from a device cache ROW
        (the prefill-specialist path when pool pressure skipped the
        donation: the chunked row is the only copy).  Same fault site
        and format as :meth:`export_image`."""
        from .kvimage import pack_image

        if _faults._armed:
            _faults.check("serve.kv_ship")
        img = pack_image(jax.tree.map(np.asarray, kc_row),
                         jax.tree.map(np.asarray, vc_row),
                         block_size=self.block_size, n_data=n_data,
                         quant=self.quant)
        return img.narrowed()

    def import_image(self, image, lanes):
        """Scatter a validated ship image's lanes into pool blocks:
        ``lanes`` maps lane index -> block id (lanes below a local
        prefix hit are simply absent — their bytes never move).  The
        ``serve.kv_ship`` fault site covers the destination half of a
        ship; validation runs BEFORE the fault check so a malformed
        image is always the typed :class:`KVImageError`, never a
        chaos artifact."""
        image.validate(self.block_size, self.quant,
                       pool_k=self.pool_k)
        if _faults._armed:
            _faults.check("serve.kv_ship")
        self.scatter_row(jax.tree.map(jnp.asarray, image.kc),
                         jax.tree.map(jnp.asarray, image.vc),
                         dict(lanes))

    def on_preempt(self):
        self._c_preempt.inc()

    def on_window_drop(self, n):
        """Account ``n`` out-of-window blocks freed by a windowed
        slot's advance (the engine already returned them via
        :meth:`free`)."""
        self.window_drops += n
        self._c_window_drop.inc(n)

    # -- lifecycle / reporting -------------------------------------------
    def unregister(self):
        """Release registry entries and the device pool (engine
        close())."""
        self._registry.remove(*self._registered)
        self.pool_k = self.pool_v = None

    def snapshot(self) -> dict:
        return {
            "block_size": self.block_size,
            "num_blocks": self.num_blocks,
            "blocks_free": self.blocks_free,
            "blocks_used": self.blocks_used,
            "preemptions": self._c_preempt.value,
            "swap_out": self._c_swap_out.value,
            "swap_in": self._c_swap_in.value,
            "quant": self.quant,
            "shared_blocks": self.shared_blocks,
        }
