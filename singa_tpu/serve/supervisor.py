"""Supervised serve-engine recovery (singa_tpu.resilience PR).

The engine's failure contract (engine.py) is "fail typed, never
wedge": a raising decode/prefill rejects every in-flight and queued
request with :class:`EngineFailedError` and marks the engine dead.
This module is the layer that turns that clean death into continuity:

* **rebuild** — the supervisor constructs a fresh engine with the SAME
  constructor arguments (same ``(max_slots, max_len)`` and statics, so
  every jitted executable is a cache hit — a restart costs an arena
  allocation, not a recompile) and a fresh KV arena.  Paged engines
  (``paged=`` forwarded verbatim) rebuild with a fresh BLOCK POOL and
  an empty radix tree: no block of the failed pool is ever carried,
  so a corrupting copy fault cannot survive a restart.  Swapped-out
  requests count as STARTED (tokens streamed before the preemption) —
  they are rejected typed, never requeued;
* **requeue** — requests the failed engine had NOT started (rejected
  with ``started=False``) are resubmitted to the new engine in their
  original arrival order; their caller-facing handles resolve as if
  the failure never happened, and their token streams are identical to
  an uninterrupted run (same seed → same private sampling chain).
  Requests that WERE in flight stay failed — tokens may already have
  streamed through ``on_token``, so silently re-running them would
  emit duplicates; the caller sees the typed error and decides;
* **restart budget** — ``restart_budget`` consecutive-lifetime
  restarts; past it, remaining work is rejected with
  :class:`RestartBudgetExceededError` (an engine that keeps dying is a
  bug, not bad luck) and the supervisor refuses further submissions.
  ``budget_reset_after_s`` (default None = consecutive-lifetime, the
  original behavior) forgives spent restarts after that much HEALTHY
  uptime since the last one: a long-lived fleet replica is then only
  condemned by crash-LOOPING (failures closer together than the
  window), never by ancient restarts accumulated over weeks;
* **SLO-pressure load shedding** — with ``shed_on_slo_pressure=True``
  and an :class:`~singa_tpu.observe.health.SLO` carrying
  ``queue_depth_max``, admission beyond that depth sheds the
  lowest-priority queued request (typed :class:`LoadShedError`,
  ``serve.shed_requests{reason=slo_pressure}``) in favor of a
  higher-priority arrival, or refuses the arrival itself when IT is
  the lowest (``reason=slo_admission``) — degrade the cheapest work
  first, before latency collapses for everyone.

Every restart increments ``resilience.engine_restarts`` (the counter
the CI chaos gate matches against injected faults) and shows up under
``health_report()["resilience"]``.
"""

from __future__ import annotations

import time

from ..observe import requests as _reqs
from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..utils.logging import get_channel
from .engine import InferenceEngine
from .request import (EngineFailedError, GenerationRequest,
                      LoadShedError, RequestHandle,
                      RestartBudgetExceededError)

__all__ = ["EngineSupervisor"]


class EngineSupervisor:
    """Own and supervise one :class:`InferenceEngine`.

    >>> sup = EngineSupervisor(model, max_slots=4, restart_budget=2)
    >>> h = sup.submit(GenerationRequest(prompt, max_new_tokens=32))
    >>> sup.run_until_complete()
    >>> h.result().tokens        # survives an engine death in between

    ``engine_kw`` is forwarded verbatim to every engine build
    (``max_slots``, ``max_len``, ``slo``, ``top_k`` ...).  Handles
    returned by :meth:`submit` are supervisor-owned: they resolve with
    the final outcome across restarts, not the first engine's."""

    def __init__(self, model, restart_budget=2,
                 budget_reset_after_s=None,
                 shed_on_slo_pressure=False, clock=time.monotonic,
                 **engine_kw):
        if restart_budget < 0:
            raise ValueError(
                f"restart_budget must be >= 0, got {restart_budget}")
        if budget_reset_after_s is not None and budget_reset_after_s <= 0:
            raise ValueError(
                f"budget_reset_after_s must be > 0 or None, got "
                f"{budget_reset_after_s}")
        self._model = model
        self._clock = clock
        self._engine_kw = dict(engine_kw, clock=clock)
        self.restart_budget = int(restart_budget)
        self.budget_reset_after_s = budget_reset_after_s
        self.restarts = 0
        self._last_restart_t = None
        self._shed = bool(shed_on_slo_pressure)
        self._slo = engine_kw.get("slo")
        self._dead = False
        # supervisor-owned completion routing: outer handles resolve
        # across engine generations (outer.request doubles as the
        # requeue source — no separate request map to keep in step)
        self._outer = {}     # request_id -> caller-facing handle
        self._inner = {}     # request_id -> current engine's handle
        self._order = []     # submission order (requeue preserves it)
        self._log = get_channel("serve")
        self._c_restarts = _registry().counter(
            "resilience.engine_restarts",
            help="supervised engine rebuilds after a typed failure")
        self.engine = InferenceEngine(model, **self._engine_kw)

    # -- submission ------------------------------------------------------
    def submit(self, request) -> RequestHandle:
        """Queue a request through the supervisor.  Raises
        :class:`LoadShedError` when SLO-pressure admission sheds the
        arrival itself, and whatever ``engine.submit`` raises
        (``QueueFullError``, ``ValueError``) otherwise."""
        if self._dead:
            raise RestartBudgetExceededError(
                f"supervisor is dead: restart budget "
                f"({self.restart_budget}) exhausted")
        if not isinstance(request, GenerationRequest):
            request = GenerationRequest(request)
        if self.engine._failed:
            # failure surfaced between steps (e.g. caller drove the
            # engine directly): recover before admitting new work
            self._recover()
        self._maybe_shed(request)
        outer = RequestHandle(request)
        inner = self.engine.submit(request)
        rid = request.request_id
        self._outer[rid] = outer
        self._inner[rid] = inner
        self._order.append(rid)
        return outer

    def _maybe_shed(self, incoming):
        """SLO-pressure admission: beyond ``queue_depth_max``, shed the
        lowest-priority queued request if it ranks strictly below the
        arrival, else refuse the arrival itself (both typed
        LoadShedError, both counted in serve.shed_requests)."""
        if not self._shed or self._slo is None \
                or self._slo.queue_depth_max is None:
            return
        if self.engine.scheduler.queue_depth < self._slo.queue_depth_max:
            return
        victim = self.engine.shed(reason="slo_pressure",
                                  below_priority=incoming.priority)
        if victim is not None:
            # the shed victim's handles are supervisor-owned too
            rid = victim.request_id
            inner = self._inner.pop(rid, None)
            outer = self._outer.pop(rid, None)
            if outer is not None and not outer.done():
                err = (inner._error if inner is not None
                       and inner._error is not None
                       else LoadShedError(f"{rid} shed (slo_pressure)"))
                outer._reject(err)
            return
        _registry().counter(
            "serve.shed_requests",
            help="queued requests shed by load-shedding admission",
            reason="slo_admission").inc()
        _trace.event("serve/shed", cat="serve", reason="slo_admission",
                     request=incoming.request_id,
                     priority=incoming.priority)
        _trace.event("serve/request_rejected", cat="serve",
                     request=incoming.request_id,
                     reason="shed:slo_admission")
        if _reqs._active:
            # refused BEFORE any engine accepted it: the ledger still
            # gets a (minimal, terminal) entry so the request log
            # shows the refusal instead of nothing
            _reqs._ledger.on_reject(
                incoming.request_id, t=self._clock(),
                reason="shed:slo_admission", started=False,
                prompt_len=len(incoming.prompt_ids),
                max_new_tokens=incoming.max_new_tokens)
        raise LoadShedError(
            f"{incoming.request_id} refused: queue at SLO pressure "
            f"(depth {self.engine.scheduler.queue_depth} >= "
            f"{self._slo.queue_depth_max}) and no queued request ranks "
            f"below priority {incoming.priority}")

    # -- drive -----------------------------------------------------------
    @property
    def pending(self) -> bool:
        return (not self._dead) and (self.engine.pending
                                     or bool(self._inner))

    def step(self) -> bool:
        """One supervised iteration: drive the engine; on a typed
        engine failure, rebuild it and requeue the never-started
        requests.  Returns ``pending``."""
        if self._dead:
            raise RestartBudgetExceededError(
                f"supervisor is dead: restart budget "
                f"({self.restart_budget}) exhausted")
        try:
            self.engine.step()
        except EngineFailedError:
            self._recover()
        self._sync()
        return self.pending

    def run_until_complete(self, max_steps=None):
        """Drive :meth:`step` until every submitted request resolves
        (normally, or typed).  Raises
        :class:`RestartBudgetExceededError` once the budget is spent —
        by then every outstanding handle is already rejected typed."""
        steps = 0
        while self.pending:
            self.step()
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(
                    f"supervisor did not drain within {max_steps} "
                    f"steps (queue={self.engine.scheduler.queue_depth},"
                    f" live={self.engine.live_slots})")

    def _sync(self):
        """Propagate resolved inner handles to the caller-facing outer
        ones and drop the routing entries."""
        done = [rid for rid, h in self._inner.items() if h.done()]
        for rid in done:
            inner = self._inner.pop(rid)
            outer = self._outer.pop(rid)
            if inner._error is not None:
                outer._reject(inner._error)
            else:
                outer._finish(inner._result)
        if done:
            live = set(self._inner)
            self._order = [r for r in self._order if r in live]

    def _recover(self):
        """Rebuild the failed engine and requeue never-started work;
        enforce the restart budget."""
        failed = self.engine
        step = failed.step_count
        # never-started requests (typed started=False by the engine)
        # are safe to requeue: no tokens streamed, same seed → same
        # chain → identical output to an uninterrupted run
        requeue = [rid for rid in self._order
                   if rid in self._inner
                   and isinstance(self._inner[rid]._error,
                                  EngineFailedError)
                   and self._inner[rid]._error.started is False]
        for rid in requeue:
            self._inner.pop(rid)
        failed.close()  # release registry entries + arena (drained:
        #                 _fail cleared every slot and the queue)
        now = self._clock()
        if (self.budget_reset_after_s is not None and self.restarts > 0
                and self._last_restart_t is not None
                and now - self._last_restart_t
                >= self.budget_reset_after_s):
            # healthy-uptime window elapsed since the last restart:
            # this failure is bad luck, not a crash loop — forgive the
            # spent budget (fleet replicas live for weeks; without
            # this, ancient restarts eventually condemn them)
            self._log.info(
                "restart budget reset after %.1fs healthy uptime "
                "(%d prior restarts forgiven)",
                now - self._last_restart_t, self.restarts)
            self.restarts = 0
        self._last_restart_t = now
        self.restarts += 1
        self._c_restarts.inc()
        _trace.event("serve/engine_restart", cat="serve",
                     restart=self.restarts, failed_step=step,
                     requeued=len(requeue))
        if self.restarts > self.restart_budget:
            self._dead = True
            err = RestartBudgetExceededError(
                f"restart budget exhausted ({self.restarts - 1} "
                f"restarts allowed); engine keeps failing")
            self._log.error("%s — rejecting %d remaining requests",
                            err, len(requeue))
            t_rej = self._clock()
            for rid in requeue:
                outer = self._outer.pop(rid, None)
                if outer is not None and not outer.done():
                    _trace.event("serve/request_rejected", cat="serve",
                                 request=rid,
                                 reason="restart_budget_exceeded")
                    if _reqs._active:
                        # the engine already sealed this timeline as a
                        # requeue-safe failure; this marks the
                        # supervisor's TERMINAL verdict on it
                        _reqs._ledger.on_reject(
                            rid, t=t_rej,
                            reason="restart_budget_exceeded",
                            started=False)
                    outer._reject(RestartBudgetExceededError(
                        f"{rid}: {err}", request_id=rid,
                        started=False))
            self._sync()
            raise err
        self._log.warning(
            "engine failed at step %d; restart %d/%d (requeueing %d "
            "never-started requests)", step, self.restarts,
            self.restart_budget, len(requeue))
        self.engine = InferenceEngine(self._model, **self._engine_kw)
        for rid in requeue:
            self._inner[rid] = self.engine.submit(
                self._outer[rid].request)
            if _reqs._active:
                # engine.submit reopened the timeline with a hop on
                # the REBUILT engine; say why the hop exists
                _reqs._ledger.annotate_hop(rid, via="supervisor_restart",
                                           restart=self.restarts)

    # -- disaggregated prefill / KV shipping (fleet-driven) --------------
    # Thin supervised wrappers over the engine's ship APIs: the fleet
    # never reaches a dead or failed engine through them.  A chunk
    # fault mid-build fails the ENGINE typed (the engine's contract);
    # the wrapper rebuilds it — restart budget enforced — and reports
    # the build dead by returning None, so the fleet restarts it from
    # scratch (nothing streamed: a replayed build is byte-identical).

    def _ship_guard(self):
        if self._dead:
            raise RestartBudgetExceededError(
                f"supervisor is dead: restart budget "
                f"({self.restart_budget}) exhausted")
        if self.engine._failed:
            self._recover()

    def start_prefix_build(self, prompt_ids):
        self._ship_guard()
        return self.engine.start_prefix_build(prompt_ids)

    def advance_prefix_build(self, job, max_tokens=None, rid=None):
        """True when complete, False when budget ran out first, None
        when the engine died mid-chunk and was rebuilt (the job is
        invalid — restart the build).  Raises
        :class:`RestartBudgetExceededError` once the budget is
        spent."""
        self._ship_guard()
        try:
            return self.engine.advance_prefix_build(
                job, max_tokens, rid=rid)
        except EngineFailedError:
            self._recover()
            self._sync()
            return None

    def export_prefix_image(self, job):
        self._ship_guard()
        return self.engine.export_prefix_image(job)

    def admit_prefix_image(self, tokens, image):
        self._ship_guard()
        return self.engine.admit_prefix_image(tokens, image)

    def abandon_prefix_build(self, job):
        if not self.engine._closed:
            self.engine.abandon_prefix_build(job)

    def abandon(self, reason="fleet failover"):
        """Fleet failover entry point: mark this supervisor dead WITHOUT
        driving the (possibly wedged) engine, and reject every
        outstanding handle typed — ``started=True`` for requests
        occupying a slot (tokens may already have streamed),
        ``started=False`` for queued/admitting ones (safe for the
        fleet to requeue on a sibling — it re-derives the requeue set
        from the rejected handles' ``started`` flags, so there is ONE
        mechanism deciding re-runnability, not two).  Idempotent: a
        supervisor that already died (budget exhausted) is a no-op —
        its handles are already rejected typed with the same started
        semantics, so the fleet's requeue scan works identically
        either way."""
        if self._dead:
            return
        self._dead = True
        started_ids = self.engine.live_request_ids
        step = self.engine.step_count
        t_ab = self._clock()
        n_requeueable = 0
        for rid in list(self._order):
            inner = self._inner.pop(rid, None)
            outer = self._outer.pop(rid, None)
            if outer is None or outer.done():
                continue
            if inner is not None and inner.done():
                # resolved in the engine but not yet synced: propagate
                # the real outcome, don't overwrite it with an abandon
                if inner._error is not None:
                    outer._reject(inner._error)
                else:
                    outer._finish(inner._result)
                continue
            started = rid in started_ids
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="abandoned",
                         started=started)
            if _reqs._active:
                # the engine never drove this rejection (abandon does
                # not touch a possibly-wedged engine), so the ledger
                # seal happens here; started=False entries reopen when
                # the fleet requeues them on a sibling
                _reqs._ledger.on_reject(rid, t=t_ab,
                                        reason=f"abandoned:{reason}",
                                        started=started)
            outer._reject(EngineFailedError(
                f"{rid}: supervisor abandoned at step {step} ({reason})",
                request_id=rid, started=started, engine_step=step))
            if not started:
                n_requeueable += 1
        self._order = []
        self._inner.clear()
        self._outer.clear()
        self._log.warning(
            "supervisor abandoned (%s): %d never-started requests "
            "rejected requeue-safe", reason, n_requeueable)
        _trace.event("serve/supervisor_abandon", cat="serve",
                     reason=str(reason), requeue=n_requeueable)

    # -- lifecycle -------------------------------------------------------
    def close(self, force=False):
        if not self.engine._closed:
            self.engine.close(force=force)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if self.engine._closed:
            return False
        return self.engine.__exit__(exc_type, *a)
