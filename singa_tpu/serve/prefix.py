"""Radix prefix cache: block-granular KV reuse for the serving engine.

Production traffic is dominated by shared prefixes — system prompts,
few-shot templates, and multi-turn sessions that re-send the whole
conversation.  The engine (engine.py) recomputed the full prefill for
every admission anyway.  This module is the RadixAttention/SGLang idea
(Zheng et al. 2023) rebuilt for the fixed-shape TPU engine:

* **block pool** — one preallocated arena of ``num_blocks`` KV blocks
  per K/V, shape ``(L, num_blocks + 1, H_kv, block_size, D)`` (the +1
  is a trash block scatter padding writes into).  A cached prefix is a
  chain of blocks; all device copies between the pool and a slot's
  cache row are ONE fixed-shape gather/scatter executable each,
  whatever the chain length, so the engine's no-runtime-recompiles
  contract survives intact;
* **radix tree** — host-side trie at block granularity: each node is
  one ``block_size``-token block keyed by its token tuple, children
  hashed under the parent.  Longest-prefix match is a dict walk per
  block.  Nodes are REF-COUNTED (in-flight requests and pinned
  sessions hold references); eviction is LRU over unreferenced
  leaves only, so a referenced block can never be freed and interior
  nodes never orphan their children;
* **canonical KV only** — the cache stores exclusively K/V produced by
  the prefill/chunked-prefill executables.  On this backend those are
  BITWISE identical to each other and invariant to the tokens beyond
  the prefix (masked causal attention contributes exact zeros), so a
  warm admission's token stream is byte-identical to cold prefill.
  Decode-step K/V is NOT canonical (measured ~1e-6 drift vs prefill
  on CPU f32), so a pinned session's generated region is
  re-canonicalized through ``gpt2_decode.prefill_chunk`` at retire
  time — one chunk pass off the TTFT path buys every later turn a
  near-full prefix hit without sacrificing parity;
* **graceful pressure** — a full pool with nothing evictable degrades
  to cold prefill (misses, skipped donations), never an error; a
  rebuilt engine (EngineSupervisor restart) starts from an empty tree
  and stays correct, just cold.

Metrics flow into the process-wide observe registry (and therefore
the health report and Prometheus export) as
``serve.prefix.{hits,misses,evictions,cached_blocks,hit_tokens,
lookup_tokens}`` with the owning engine's label.  The
``serve.prefix_copy`` fault site (singa_tpu.resilience) covers the
pool<->row copy paths: an injected copy failure fails the engine
TYPED and the supervisor rebuild path recovers with an empty cache
(bench_chaos.py asserts zero wedged/lost requests under it).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..observe import requests as _reqs
from ..observe.registry import registry as _default_registry
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["PrefixCacheConfig", "PrefixCache", "SessionHandle",
           "FleetPrefixIndex"]


@dataclass(frozen=True)
class PrefixCacheConfig:
    """Knobs for the engine's prefix cache (hand to
    ``model.serve(prefix_cache=...)``; the supervisor forwards it
    verbatim to every rebuilt engine, which is what makes restart
    recovery rebuild-from-empty by construction).

    ``block_size``: tokens per cached block — the reuse granularity.
    Smaller blocks match more of a ragged prefix but cost more tree
    nodes per token; the engine requires ``max_len % block_size == 0``
    so chunked prefill windows never cross the arena edge.
    ``num_blocks``: pool capacity in blocks (device memory:
    ``2 * L * num_blocks * H_kv * block_size * D`` elements)."""

    block_size: int = 64
    num_blocks: int = 256

    def __post_init__(self):
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 1:
            raise ValueError(
                f"num_blocks must be >= 1, got {self.num_blocks}")


# -- fixed-shape device copies ----------------------------------------------
# Shapes are keyed on (pool, row) geometry only: every call below is
# compiled once per engine and reused for any chain length, because the
# block-index vector is always the full row's worth of block slots
# (W // block_size entries) with unused lanes masked / pointed at the
# trash block.  PYTREE-GENERIC since the paged round (the leaf helpers
# live in serve/paged.py): dense pools are plain arrays, int8 pools are
# (values, scales) tuples — the per-leaf block width comes off the
# leaf's own shape, so the trailing-axis-free scales leaf rides the
# same executables.  This is what lifted the old int8 + prefix-cache
# refusal.

@jax.jit
def _blocks_to_row(pool_k, pool_v, idx, n_used):
    """Gather ``idx`` (nb,) pool blocks into a fresh (L, 1, H, W, ...)
    cache row per leaf: block j covers positions [j*B, (j+1)*B).
    Lanes ``>= n_used`` (traced) are zeroed — junk that the chunked
    prefill and the decode mask never read live."""
    from .paged import _leaf_to_row

    def gather(pool):
        return _leaf_to_row(pool, idx, n_used, pool.shape[3])

    return jax.tree.map(gather, pool_k), jax.tree.map(gather, pool_v)


@partial(jax.jit, donate_argnums=(0, 1))
def _row_to_blocks(pool_k, pool_v, kc_row, vc_row, idx):
    """Scatter a cache row's blocks into the pool at ``idx`` (nb,)
    block slots.  Lanes that should not store anything point at the
    trash block (index ``num_blocks``, reserved by the pool for
    exactly this) so one executable serves every donation size.
    Duplicate trash-lane writes collide only with each other.  The
    pool buffers are DONATED (the caller rebinds) — without that,
    every retirement's donation would copy the whole pool (hundreds
    of MB at production block counts) instead of scattering in
    place."""
    from .paged import _leaf_to_pool

    def scatter(pool, row):
        return _leaf_to_pool(pool, row, idx, pool.shape[3])

    return (jax.tree.map(scatter, pool_k, kc_row),
            jax.tree.map(scatter, pool_v, vc_row))


@jax.jit
def _read_slot(kc_arena, vc_arena, slot):
    """One slot's cache rows (L, 1, H, W, ...) out of the engine
    arena (per leaf — int8 arenas are (values, scales) tuples whose
    scales leaf lacks the trailing D axis)."""

    def rd(arena):
        sizes = (arena.shape[0], 1) + arena.shape[2:]
        start = (0, slot) + (0,) * (arena.ndim - 2)
        return jax.lax.dynamic_slice(arena, start, sizes)

    return jax.tree.map(rd, kc_arena), jax.tree.map(rd, vc_arena)


class _Node:
    """One cached block: ``key`` is the tuple of its block_size tokens,
    ``block`` its pool slot.  ``refs`` counts in-flight admissions and
    pinned sessions holding it; ``last_used`` is a logical LRU clock
    tick (deterministic — no wall time)."""

    __slots__ = ("key", "parent", "children", "block", "refs",
                 "last_used")

    def __init__(self, key, parent, block, tick):
        self.key = key
        self.parent = parent
        self.children = {}
        self.block = block
        self.refs = 0
        self.last_used = tick


class SessionHandle:
    """A finished request's sequence, pinned for multi-turn
    continuation.  ``tokens`` is the full prompt + generation;
    :meth:`request` builds the next turn's ``GenerationRequest`` with
    the conversation re-sent as its prompt — against a warm cache the
    whole pinned history is a block-prefix hit, so the next turn
    prefills only the new user tokens.  Works (cold) against a
    restarted engine's empty cache too: the handle owns host tokens,
    not device state.  :meth:`release` unpins the cached path; a
    released or restart-orphaned handle keeps building valid requests.
    """

    def __init__(self, tokens, cache=None, nodes=()):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self._cache = cache
        self._nodes = list(nodes)

    @property
    def pinned_blocks(self) -> int:
        return len(self._nodes)

    def request(self, extra_tokens, **kw):
        """The next turn: a GenerationRequest whose prompt is this
        session's full sequence + ``extra_tokens`` (the new user
        input).  Keyword args pass through to GenerationRequest
        (``max_new_tokens``, ``temperature``, ``pin_session`` for the
        turn after this one, ...).  The request carries
        ``session_of=self`` so a fleet router can keep the continuation
        on the replica whose cache holds the pinned blocks."""
        from .request import GenerationRequest
        extra = np.asarray(extra_tokens, np.int32).reshape(-1)
        kw.setdefault("session_of", self)
        return GenerationRequest(
            np.concatenate([self.tokens, extra]), **kw)

    def release(self):
        """Unpin the session's cached path (idempotent).  The blocks
        stay cached until LRU pressure evicts them."""
        if self._cache is not None and self._nodes:
            self._cache.release(self._nodes)
        self._nodes = []


class _IndexNode:
    """One fleet-index block: children keyed by token tuple, the set
    of replica indices whose trees were seen holding this block, and
    a logical recency tick (the capacity bound's eviction order)."""

    __slots__ = ("children", "replicas", "tick")

    def __init__(self, tick=0):
        self.children = {}
        self.replicas = set()
        self.tick = tick


class FleetPrefixIndex:
    """FLEET-level residency index over the replicas' radix trees (the
    disaggregation round): one host-side trie at block granularity
    mapping token-block paths to the set of replica indices whose
    prefix caches hold them — the structure that makes the prefix
    cache a fleet resource instead of N private copies.

    The index is a HINT, not ground truth: per-replica LRU eviction
    never notifies the fleet, so every consumer verifies a candidate
    against the source replica's LIVE tree (``PrefixCache.lookup``)
    before acting on it — a stale entry degrades to a cold prefill or
    a fresh ship, never to an error.  Registration happens at the
    fleet's observation points (a prefill specialist's donation, a
    ship landing on a decode replica); ``drop_replica`` clears a
    failed-over or revived replica wholesale (its rebuilt tree starts
    empty), ``unregister`` prunes a hint a failed verify just proved
    stale, and ``max_blocks`` bounds the trie (least-recently-touched
    root subtree evicted first — the host-memory discipline every
    bounded store in the codebase keeps)."""

    def __init__(self, block_size, max_blocks=4096):
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        if max_blocks < 1:
            raise ValueError(
                f"max_blocks must be >= 1, got {max_blocks}")
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks)
        self._count = 0
        self._ticks = itertools.count(1)
        self._root = _IndexNode()

    def _keys(self, tokens, n_blocks):
        B = self.block_size
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = min(int(n_blocks), len(toks) // B)
        return [tuple(int(t) for t in toks[j * B:(j + 1) * B])
                for j in range(n)]

    def register(self, tokens, n_blocks, replica):
        """Record that ``replica`` holds the first ``n_blocks`` blocks
        of ``tokens`` (refreshes recency; may evict the stalest root
        subtree to stay within ``max_blocks``)."""
        tick = next(self._ticks)
        node = self._root
        for key in self._keys(tokens, n_blocks):
            child = node.children.get(key)
            if child is None:
                child = _IndexNode(tick)
                node.children[key] = child
                self._count += 1
            child.replicas.add(int(replica))
            child.tick = tick
            node = child
        self._prune()

    def _subtree_size(self, node):
        return 1 + sum(self._subtree_size(c)
                       for c in node.children.values())

    def _prune(self):
        """Hold the trie at ``max_blocks`` nodes: evict whole root
        subtrees, least-recently-touched first (the just-registered
        path carries the max tick, so it is never its own victim).
        Unbounded growth is the alternative — hints are only ever
        removed by failover otherwise, and a long-running fleet
        serving unique prompts would leak host memory forever."""
        while self._count > self.max_blocks and self._root.children:
            key = min(self._root.children,
                      key=lambda k: self._root.children[k].tick)
            victim = self._root.children.pop(key)
            self._count -= self._subtree_size(victim)

    def unregister(self, tokens, n_blocks, replica):
        """Drop ``replica`` from the first ``n_blocks`` blocks'
        residency sets (a verify against its live tree just failed —
        the hint is stale) and prune nodes nobody holds."""
        replica = int(replica)
        path = []
        node = self._root
        for key in self._keys(tokens, n_blocks):
            child = node.children.get(key)
            if child is None:
                break
            child.replicas.discard(replica)
            path.append((node, key, child))
            node = child
        for parent, key, child in reversed(path):
            if not child.replicas and not child.children:
                del parent.children[key]
                self._count -= 1

    def holders(self, tokens, n_blocks) -> list:
        """Replica indices whose registered residency covers ALL of
        the first ``n_blocks`` blocks, ascending (deterministic) —
        the targeted-ship source / local-warm routing candidates.
        Empty when nothing covers the whole span."""
        keys = self._keys(tokens, n_blocks)
        if len(keys) < n_blocks or not keys:
            return []
        node, held = self._root, None
        for key in keys:
            node = node.children.get(key)
            if node is None:
                return []
            held = (set(node.replicas) if held is None
                    else held & node.replicas)
            if not held:
                return []
        return sorted(held)

    def drop_replica(self, replica):
        """Forget every residency record for ``replica`` (failover or
        revive: the rebuilt tree is empty) and prune nodes no replica
        holds."""
        replica = int(replica)

        def sub(node):
            node.replicas.discard(replica)
            dead = [k for k, c in node.children.items()
                    if not sub(c)]
            for k in dead:
                del node.children[k]
            return bool(node.replicas or node.children)

        sub(self._root)
        self._count = self._subtree_size(self._root) - 1

    def snapshot(self) -> dict:
        return {"block_size": self.block_size,
                "max_blocks": self.max_blocks,
                "indexed_blocks": self._count}


class PrefixCache:
    """Block-granular radix tree over a pooled KV arena (module
    docstring).  Owned by one engine; the engine drives every device
    copy through the fixed-shape helpers above and this class keeps
    the host-side tree, refcounts, LRU state, and metrics."""

    def __init__(self, config, n_layer, n_kv_head, head_dim, dtype,
                 engine_label="0", reg=None, quant=False, arena=None,
                 tp=None):
        self.config = config
        B, N = config.block_size, config.num_blocks
        self.block_size = B
        self.num_blocks = N
        # ARENA mode (paged engines): the tree indexes blocks of the
        # engine's shared PagedKVArena instead of owning a pool —
        # capacity is the arena's, device copies route through it, and
        # donation is zero-copy adoption (adopt_blocks)
        self._arena = arena
        # tensor-parallel executor (serve/tp.py): cache rows and the
        # cache-owned pool become SHARDED pytrees over the tp mesh's
        # H_kv axis, and the pool<->row copies dispatch through the
        # executor's sharded twins.  The host-side radix tree, ref
        # counts, and LRU state are untouched — a cached block is the
        # same logical block on every shard
        self._tp = tp
        if arena is not None:
            self.num_blocks = arena.num_blocks
            self._pool_k = self._pool_v = None
        else:
            if quant:
                # (values, scales) pytree pool — same layout as the
                # int8 engine arena, so the generic copies round-trip
                self._pool_k = (
                    jnp.zeros((n_layer, N + 1, n_kv_head, B, head_dim),
                              jnp.int8),
                    jnp.zeros((n_layer, N + 1, n_kv_head, B),
                              jnp.float32))
                self._pool_v = (
                    jnp.zeros((n_layer, N + 1, n_kv_head, B, head_dim),
                              jnp.int8),
                    jnp.zeros((n_layer, N + 1, n_kv_head, B),
                              jnp.float32))
            else:
                # +1: trash block scatter padding lands in (never read)
                self._pool_k = jnp.zeros((n_layer, N + 1, n_kv_head, B,
                                          head_dim), dtype)
                self._pool_v = jnp.zeros_like(self._pool_k)
            if tp is not None:
                self._pool_k = tp.place_cache(self._pool_k)
                self._pool_v = tp.place_cache(self._pool_v)
        self._root = _Node((), None, -1, 0)
        self._free = [] if arena is not None else list(range(N))
        self._nodes_by_block = {}       # pool slot -> node
        self._tick = itertools.count(1)
        self._log = get_channel("serve")
        reg = reg if reg is not None else _default_registry()
        lbl = dict(engine=engine_label)
        self._c_hits = reg.counter(
            "serve.prefix.hits",
            help="admissions that reused >=1 cached block", **lbl)
        self._c_misses = reg.counter(
            "serve.prefix.misses",
            help="admissions with no usable cached prefix", **lbl)
        self._c_evictions = reg.counter(
            "serve.prefix.evictions",
            help="LRU evictions of unreferenced leaf blocks", **lbl)
        self._c_hit_tokens = reg.counter(
            "serve.prefix.hit_tokens",
            help="prompt tokens served from cached blocks", **lbl)
        self._c_lookup_tokens = reg.counter(
            "serve.prefix.lookup_tokens",
            help="prompt tokens seen by admission lookups", **lbl)
        self._c_donate_skipped = reg.counter(
            "serve.prefix.donate_skipped",
            help="blocks not cached because the pool was full of "
                 "referenced blocks", **lbl)
        self._g_cached = reg.gauge(
            "serve.prefix.cached_blocks",
            help="blocks currently held by the radix tree", **lbl)
        self._registry = reg
        self._registered = [
            self._c_hits, self._c_misses, self._c_evictions,
            self._c_hit_tokens, self._c_lookup_tokens,
            self._c_donate_skipped, self._g_cached]

    # -- tree ------------------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        return len(self._nodes_by_block)

    def cached_block_ids(self):
        """Pool slots the radix tree currently owns — the cache's side
        of the engine's block-accounting invariant (every used arena
        block is either cached here or owned by a live/prefilling
        row)."""
        return list(self._nodes_by_block)

    def _block_keys(self, tokens):
        B = self.block_size
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = len(toks) // B
        return [tuple(int(t) for t in toks[j * B:(j + 1) * B])
                for j in range(n)]

    def lookup(self, tokens):
        """Longest cached block-prefix of ``tokens``: the matched node
        path, root-first.  Pure — no counters, no refcounts (the
        scheduler's admission-cost probe uses it too).  Block keys are
        built lazily so an early miss (block 0 of a long prompt) does
        no O(prompt_len) tuple work."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        B = self.block_size
        path = []
        node = self._root
        for j in range(len(toks) // B):
            key = tuple(int(t) for t in toks[j * B:(j + 1) * B])
            node = node.children.get(key)
            if node is None:
                break
            path.append(node)
        return path

    def touch(self, nodes):
        """Refresh LRU recency for an already-cached path (the
        donation short-circuit: nothing to copy, but the path was
        just used)."""
        tick = next(self._tick)
        for n in nodes:
            n.last_used = tick

    def acquire(self, nodes):
        """Pin a matched path for the lifetime of an in-flight request
        (or a session): referenced nodes are never evicted, so a hot
        prefix cannot be churned out from under its users."""
        tick = next(self._tick)
        for n in nodes:
            n.refs += 1
            n.last_used = tick

    def release(self, nodes):
        for n in nodes:
            n.refs -= 1
            if n.refs < 0:
                # a real exception, not an assert (-O strips asserts):
                # underflow would let a still-pinned block read as
                # unreferenced and be evicted under a live session
                n.refs = 0
                raise RuntimeError(
                    "prefix-cache refcount underflow (double release "
                    f"of block {n.block})")

    def on_donate_skipped(self, n):
        """Account ``n`` blocks that could not be cached under pool
        pressure (the engine's ship-export path under a failed
        allocation — :meth:`donate_from_row` counts its own)."""
        self._c_donate_skipped.inc(int(n))

    def on_admit(self, hit_blocks, prompt_len, request_id=None):
        """Metrics for one admission: ``hit_blocks`` usable cached
        blocks against a ``prompt_len``-token prompt.  With the
        request ledger on, also annotates the request's timeline with
        the authoritative cold/warm verdict and hit-token count (the
        cache owns hit accounting; the engine only owns timing)."""
        self._c_lookup_tokens.inc(int(prompt_len))
        if hit_blocks > 0:
            self._c_hits.inc()
            self._c_hit_tokens.inc(int(hit_blocks) * self.block_size)
        else:
            self._c_misses.inc()
        if _reqs._active and request_id is not None:
            _reqs._ledger.on_prefix(
                request_id,
                hit_tokens=int(hit_blocks) * self.block_size)

    # -- allocation / eviction -------------------------------------------
    def _evict_one(self):
        """Drop the least-recently-used UNREFERENCED LEAF.  Interior
        nodes and referenced nodes are untouchable: evicting an
        interior node would orphan its children's match path, and a
        referenced one is in use.  Returns the freed pool slot or
        None."""
        victim = None
        for node in self._nodes_by_block.values():
            if node.refs > 0 or node.children:
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        if victim is None:
            return None
        del victim.parent.children[victim.key]
        del self._nodes_by_block[victim.block]
        self._c_evictions.inc()
        self._g_cached.set(self.cached_blocks)
        return victim.block

    def evictable_blocks(self) -> int:
        """How many blocks LRU eviction could EVER reclaim: a node is
        reclaimable only after its whole subtree is (evicting an
        interior node would orphan children), so a referenced node
        shields every ancestor.  The paged engine's allocation
        feasibility check uses this to avoid preempting live work for
        an allocation that could never fit anyway (pinned sessions
        holding the pool)."""

        def sub(node):
            # (evictable count, whole subtree reclaimable)
            total, fully = 0, True
            for c in node.children.values():
                ev, f = sub(c)
                total += ev
                fully = fully and f
            if fully and node.refs == 0:
                return total + 1, True
            return total, False

        return sum(sub(c)[0] for c in self._root.children.values())

    def _alloc(self):
        if self._free:
            return self._free.pop()
        return self._evict_one()

    # -- device copies (engine-driven) -----------------------------------
    def _pad_idx(self, blocks, trash):
        """Fixed-width block-index vector: real entries then ``trash``
        padding, so one executable serves every chain length."""
        nb = len(blocks)
        idx = np.full(self._row_blocks, trash, np.int32)
        idx[:nb] = blocks
        return jnp.asarray(idx)

    def attach_row_geometry(self, max_len):
        """Called once by the owning engine: the number of blocks a
        full cache row spans (the fixed width of every copy's index
        vector)."""
        assert max_len % self.block_size == 0
        self._row_blocks = max_len // self.block_size

    def copy_into_row(self, nodes):
        """Build a cache row holding ``nodes``' blocks at positions
        [0, len(nodes)*B); the rest zeros.  One gather dispatch — out
        of the shared paged arena in arena mode (its
        ``serve.paged_copy`` fault site covers that path), out of the
        cache-owned pool otherwise."""
        if self._arena is not None:
            return self._arena.gather_row([n.block for n in nodes],
                                          n_used=len(nodes))
        if _faults._armed:
            _faults.check("serve.prefix_copy")
        idx = self._pad_idx([n.block for n in nodes], trash=0)
        if self._tp is not None:
            return self._tp.pool_to_row(self._pool_k, self._pool_v,
                                        idx, jnp.int32(len(nodes)))
        return _blocks_to_row(self._pool_k, self._pool_v, idx,
                              jnp.int32(len(nodes)))

    def adopt_blocks(self, tokens, blocks, n_goal):
        """ZERO-COPY donation (arena mode): insert tree nodes that
        take OWNERSHIP of a retiring slot's private pool blocks —
        ``blocks[j]`` holds the canonical K/V for token block ``j`` of
        ``tokens``, already sitting in the shared paged arena, so
        donation moves a pointer, not bytes.  A lane that ALREADY has
        a node (the slot's shared admission prefix, or a sibling's
        earlier donation of the same content) keeps the tree's block
        and the caller frees any duplicate (it is absent from the
        returned path's block set).  Never skips, never allocates:
        adoption cannot fail under pool pressure.  Returns the tree
        path covering ``n_goal`` blocks."""
        keys = self._block_keys(tokens)[:n_goal]
        tick = next(self._tick)
        path = []
        node = self._root
        for j, key in enumerate(keys):
            child = node.children.get(key)
            if child is None:
                child = _Node(key, node, blocks[j], tick)
                node.children[key] = child
                self._nodes_by_block[blocks[j]] = child
            child.last_used = tick
            path.append(child)
            node = child
        self._g_cached.set(self.cached_blocks)
        return path

    def donate_from_row(self, tokens, kc_row, vc_row, n_blocks):
        """Insert ``tokens``' first ``n_blocks`` full blocks into the
        tree, copying the missing ones out of the (canonical) cache
        row in ONE scatter dispatch.  Under pool pressure the
        donation stops at the first unallocatable block (the stored
        path must stay a contiguous prefix) — counted, never raised.
        Returns the tree path covering what is now cached.  Arena-mode
        caches never call this — the paged engine donates by
        :meth:`adopt_blocks` (zero copy)."""
        if self._arena is not None:
            raise RuntimeError(
                "donate_from_row on an arena-backed prefix cache: "
                "paged engines donate by adoption (adopt_blocks)")
        if _faults._armed:
            _faults.check("serve.prefix_copy")
        keys = self._block_keys(tokens)[:n_blocks]
        tick = next(self._tick)
        path, new_nodes = [], []
        node = self._root
        try:
            for j, key in enumerate(keys):
                child = node.children.get(key)
                if child is None:
                    slot = self._alloc()
                    if slot is None:
                        self._c_donate_skipped.inc(len(keys) - j)
                        break
                    child = _Node(key, node, slot, tick)
                    node.children[key] = child
                    self._nodes_by_block[slot] = child
                    new_nodes.append((j, child))
                # transient ref: the in-progress path must not be LRU
                # fodder for its OWN later allocations (an evicted
                # ancestor would orphan the blocks donated under it)
                child.refs += 1
                child.last_used = tick
                path.append(child)
                node = child
            if new_nodes:
                idx = np.full(self._row_blocks, self.num_blocks,
                              np.int32)
                for j, child in new_nodes:
                    idx[j] = child.block
                if self._tp is not None:
                    self._pool_k, self._pool_v = self._tp.row_to_pool(
                        self._pool_k, self._pool_v, kc_row, vc_row,
                        jnp.asarray(idx))
                else:
                    self._pool_k, self._pool_v = _row_to_blocks(
                        self._pool_k, self._pool_v, kc_row, vc_row,
                        jnp.asarray(idx))
                self._g_cached.set(self.cached_blocks)
        finally:
            for n in path:
                n.refs -= 1
        return path

    # -- lifecycle / reporting -------------------------------------------
    def unregister(self):
        """Release registry entries and the device pool (engine
        close(); in arena mode the shared pool is the arena's to
        release)."""
        self._registry.remove(*self._registered)
        self._pool_k = self._pool_v = None

    def snapshot(self) -> dict:
        lookup = self._c_lookup_tokens.value
        return {
            "block_size": self.block_size,
            "capacity_blocks": self.num_blocks,
            "cached_blocks": self.cached_blocks,
            "hits": self._c_hits.value,
            "misses": self._c_misses.value,
            "evictions": self._c_evictions.value,
            "hit_tokens": self._c_hit_tokens.value,
            "lookup_tokens": lookup,
            "donate_skipped": self._c_donate_skipped.value,
            "hit_rate_tokens": (self._c_hit_tokens.value / lookup
                                if lookup else 0.0),
        }
