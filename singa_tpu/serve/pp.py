"""Pipeline-parallel serving: one engine's LAYERS partitioned into
stages across a ``pp`` mesh axis, each stage owning its layer slice of
the paged KV pool (the EP/PP-serve round; GPipe's microbatch schedule
applied to continuous-batching decode — ROADMAP item 4's second half,
"models bigger than any single mesh group").

serve/tp.py shards a model WIDE (every layer split across shards);
this module shards it DEEP: a model whose layer stack exceeds one
device's memory serves with stage ``s`` holding layers
``[s*L/P, (s+1)*L/P)`` — the stage split ``parallel/pipeline.py`` uses
for training, restated against the decode pytree.  Third executor
behind the pluggable ``engine._x`` seam:

* **placement** — the per-layer block dicts STACK into (L, ...) arrays
  sharded ``P(pp)`` on the layer axis (each rank materializes only its
  L/P resident layers — the memory win), embeddings/norms/LM-head
  replicated; the paged block pool shards the SAME way:
  ``(L/P, num_blocks+1, H_kv, B, D)`` per stage with GLOBAL block ids,
  so the host-side free list, block tables, radix tree, scheduler,
  preemption/swap bookkeeping, and request ledger run unchanged;
* **microbatched decode** — the jitted pool step runs the GPipe
  schedule over the live continuous batch: the dispatch's slot lanes
  split into M microbatches (``PPConfig(microbatches=)``, clamped by
  gcd to the compacted dispatch width), and each of the ``M + P - 1``
  ticks advances every stage on a different microbatch with
  activations hopping one ``lax.ppermute`` forward — bubbles amortize
  across the batch (fraction ``(P-1)/(M+P-1)``), each rank
  reads/writes only ITS pool slice for the microbatch it is serving,
  and the last stage samples (the same ``_select_sample`` chain) and
  masked-psums tokens + carried keys back to every rank;
* **prefill / warm chunks** — cold admissions and block-width chunk
  windows flow stage-to-stage as one wave (a single row has no
  microbatch parallelism to mine — prefill through a pipeline is
  latency-sequential by construction); every rank runs its resident
  layers per wave and keeps its own K/V via a rank mask, so the cache
  rows come back layer-sharded exactly like the pool.  SPMD honesty:
  each rank traces every wave (its stage on the rotating buffer), so
  a P-stage prefill pays ~P× the FLOPs of the serial one in garbage
  waves — static shapes over compute waste, the standard shard_map
  trade, documented in docs/SERVING.md;
* **parity** — PP streams are pinned token-identical to the
  single-device paged engine (cold/warm/int8/preempt-resume, greedy +
  seeded — tests/test_pp_serve.py): no arithmetic is reordered (layers
  run in the same order with the same per-layer kernels; ppermute
  moves bytes, not sums), so the pin is strictly tighter than TP's
  psum caveat;
* **swap / preemption** — the pool<->row copy twins run with
  ``P(pp)`` layer-axis specs; ``swap_out``'s ``np.asarray`` assembles
  the full layer axis, so a preempted PP request's host image is
  byte-compatible with the single-device engine's (the same cross-
  geometry guarantee TP gives on the head axis).

Twins are cached MODULE-WIDE keyed like TP's (supervisor rebuild or
an identical fleet replica = compile-cache hit; counted by
``bench_serve._serve_jit_cache_size``).  Every sharded dispatch checks
the ``serve.pp_boundary`` fault site: an injected fault is a raising
stage-boundary hop — the engine fails TYPED and the supervisor
rebuilds (bench_chaos.py ``chaos_pp`` gates zero wedged/lost/leaked).

Scope (every refusal typed at construction, BEFORE any registry
registration): requires ``paged=`` with the block kernel (the tentpole
memory model — per-stage block pools); ``stages`` must divide
``n_layer``; dense/GQA models only (MoE stacks heterogeneous block
dicts — serve MoE with ``ep=``); no speculative draft (the draft's
sequential proposal scan would serialize the pipeline, and a draft of
mismatched depth cannot even take the stage split); no sliding
window; no plan-sharded models; ``pp`` composes with paged + prefix
cache + int8 + chunked-prefill budget.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observe import trace as _trace
from ..observe.registry import registry as _default_registry
from ..parallel.sharding import PP as PP_AXIS
from ..parallel.sharding import create_pp_mesh
from ..resilience import faults as _faults
from ..utils.logging import get_channel

__all__ = ["PPConfig", "PPExecutor", "fleet_pp_configs"]

#: replicated spec over the 1-D pp mesh
_R = P()
#: every KV leaf (pool, cache row, scales): LAYER axis (axis 0) over pp
_LS = P(PP_AXIS)

# module-wide twin cache, keyed like tp.py's
_TWINS = {}


def _twin_cache_size():
    """Compiled-signature count across every cached PP twin — counted
    by ``bench_serve._serve_jit_cache_size``."""
    total = 0
    for f in _TWINS.values():
        try:
            total += f._cache_size()
        except Exception:
            return None
    return total


@dataclass(frozen=True)
class PPConfig:
    """Knobs for the pipeline-parallel serve backend (hand to
    ``model.serve(pp=...)`` — a bare int is shorthand for
    ``PPConfig(stages=k)``; the supervisor/fleet forward it verbatim
    so a rebuilt replica lands on the SAME device group).

    ``stages``: pipeline depth (must divide ``n_layer``; 1 = off).
    ``microbatches``: decode microbatch count — the GPipe bubble
    knob: a pool step splits its slot lanes into this many
    microbatches so stages overlap on different lanes (bubble
    fraction (stages-1)/(microbatches+stages-1)).  Clamped per
    dispatch to gcd(microbatches, dispatch width) so the compacted
    width buckets stay legal.  Default: ``stages``.
    ``devices``: explicit device tuple (default: the first ``stages``
    of ``jax.devices()``) — the fleet hands each PP replica a
    disjoint stage-wide group (:func:`fleet_pp_configs`)."""

    stages: int = 2
    microbatches: int | None = None
    devices: tuple | None = None

    def __post_init__(self):
        if self.stages < 1:
            raise ValueError(f"stages must be >= 1, got {self.stages}")
        if self.microbatches is not None and self.microbatches < 1:
            raise ValueError(
                f"microbatches must be >= 1 (or None for one per "
                f"stage), got {self.microbatches}")
        if self.devices is not None \
                and len(self.devices) < self.stages:
            raise ValueError(
                f"PPConfig(stages={self.stages}) with only "
                f"{len(self.devices)} explicit devices")

    @property
    def mb(self):
        return (self.stages if self.microbatches is None
                else int(self.microbatches))


def as_pp_config(pp):
    """Normalize the ``pp=`` knob (bare int stage count, kwargs dict,
    or a PPConfig) — the ONE coercion the engine and the fleet both
    apply."""
    if isinstance(pp, PPConfig):
        return pp
    if isinstance(pp, int) and not isinstance(pp, bool):
        return PPConfig(stages=pp)
    if isinstance(pp, dict):
        return PPConfig(**pp)
    raise ValueError(
        f"pp must be an int stage count, a PPConfig, or a kwargs "
        f"dict, got {type(pp)}")


def check_pp(config, cfg, model_plan=None, paged=None,
             draft_model=None, window=None):
    """The full PP composition/validity matrix, TYPED — callable
    BEFORE any registry/executor/arena state exists (the engine runs
    it first so a refused construction leaks no metrics)."""
    if model_plan is not None:
        raise ValueError(
            "pp= on a plan-sharded model: the training ShardingPlan "
            "already owns the weight layout; build the serve model "
            "without a plan and let the PP backend place the decode "
            "weights")
    if getattr(cfg, "moe_every", None) is not None:
        raise ValueError(
            f"pp={config.stages} on an MoE model: MoE and dense "
            f"blocks carry different weight sets, so the layer stack "
            f"cannot stack into the stage-sharded (L, ...) arrays — "
            f"serve MoE models with ep=EPConfig(ep=, tp=) "
            f"(singa_tpu/serve/ep.py)")
    # mesh first: "stages wider than the machine" is the clearer
    # error when both it and the divisibility check would fire (the
    # same ordering serve/tp.py keeps)
    devs = (config.devices if config.devices is not None
            else jax.devices())
    if len(devs) < config.stages:
        raise ValueError(
            f"stages={config.stages} needs {config.stages} devices, "
            f"have {len(devs)} — provision a virtual CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{config.stages} or lower stages")
    if cfg.n_layer % config.stages != 0:
        raise ValueError(
            f"stages={config.stages} does not divide n_layer "
            f"({cfg.n_layer}): every stage must own a whole number "
            f"of layers (and the paged pool's layer axis slices the "
            f"same way)")
    if paged is None or paged is False:
        raise ValueError(
            "pp= requires paged=: the pipeline's memory model IS the "
            "per-stage slice of the paged block pool "
            "(docs/SERVING.md 'Expert-parallel and pipeline "
            "serving'); the slot arena has no stage split")
    kern = (paged.kernel if hasattr(paged, "kernel")
            else paged.get("kernel", "block")
            if isinstance(paged, dict) else "block")
    if kern != "block":
        raise ValueError(
            f"pp= requires PagedConfig(kernel='block'), got {kern!r}: "
            f"the stage bodies run the per-layer block-native kernel "
            f"directly over their pool slice — the gather oracle "
            f"materializes full rows no stage owns")
    if draft_model is not None:
        raise ValueError(
            f"pp= with a speculative draft: the draft's spec_k "
            f"sequential proposal scan would serialize every "
            f"pipeline tick, and a draft of mismatched depth "
            f"({getattr(draft_model.cfg, 'n_layer', '?')} layers vs "
            f"{config.stages} stages) cannot take the stage split at "
            f"all; serve speculative traffic on tp=/ep= engines")
    if window is not None:
        raise NotImplementedError(
            "pp= on a sliding-window model is not implemented (the "
            "windowed block-drop bookkeeping is untested against "
            "stage-sliced pools); serve windowed models with tp= or "
            "single-device paged engines")


def fleet_pp_configs(pp, replicas, devices=None):
    """Disjoint per-replica :class:`PPConfig`\\ s: replica ``i`` owns
    the stage-wide device group ``[i*stages, (i+1)*stages)`` —
    pipeline parallelism inside each replica, data parallelism across
    them."""
    pp = as_pp_config(pp)
    if pp.stages == 1:
        return [pp] * replicas
    devs = (list(pp.devices) if pp.devices is not None
            else list(jax.devices()))
    need = pp.stages * replicas
    if need > len(devs):
        raise ValueError(
            f"stages x replicas ({pp.stages} x {replicas} = {need}) "
            f"exceeds the {len(devs)}-device mesh; shrink the fleet "
            f"or the stage count, or provision a larger virtual mesh "
            f"via XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{need}")
    return [PPConfig(stages=pp.stages, microbatches=pp.microbatches,
                     devices=tuple(devs[i * pp.stages:
                                        (i + 1) * pp.stages]))
            for i in range(replicas)]


def _stack_blocks(blocks):
    """Stack the per-layer block dicts into one dict of (L, ...)
    arrays — the stage-shardable layout (parallel/pipeline.py's
    stacked-parameter idiom restated for the decode pytree).  Typed
    refusal on heterogeneous stacks is check_pp's job (MoE)."""
    keys = blocks[0].keys()
    return {k: jnp.stack([b[k] for b in blocks]) for k in keys}


class PPExecutor:
    """The engine's pipeline-parallel executor: owns the ``pp`` mesh,
    the stage-stacked weight placement, the GPipe-scheduled sharded
    twins, and the ``serve.pp.*`` metrics.  Built by
    ``InferenceEngine`` when ``pp=`` is set; exposes the same surface
    ``_LocalExec``/``TPExecutor``/``EPExecutor`` do."""

    def __init__(self, config, cfg, statics, quant, model_plan=None,
                 engine_label="0", reg=None):
        # defensive re-validation (the engine already ran the full
        # matrix BEFORE any registration; direct users get the same
        # typed errors here, still before this executor registers)
        if model_plan is not None or \
                getattr(cfg, "moe_every", None) is not None or \
                cfg.n_layer % config.stages != 0:
            check_pp(config, cfg, model_plan=model_plan,
                     paged=_BlockKernelSentinel())
        self.mesh = create_pp_mesh(config.stages,
                                   devices=config.devices)
        self.config = config
        self.stages = int(config.stages)
        self.microbatches = int(config.mb)
        self.n_layer = int(cfg.n_layer)
        self._statics = dict(statics)
        self._quant = bool(quant)
        self._chunk = None
        self._window = None
        self._pspec = None
        self._layer_sh = NamedSharding(self.mesh, _LS)
        self._repl_sh = NamedSharding(self.mesh, _R)
        self._kv_bytes = 0
        self._log = get_channel("serve")
        self._key = (self.stages, self.microbatches,
                     tuple(int(d.id) for d in self.mesh.devices.flat),
                     tuple(sorted(self._statics.items())),
                     self._quant)
        reg = reg if reg is not None else _default_registry()
        lbl = dict(engine=engine_label)
        self._g_stages = reg.gauge(
            "serve.pp.stages",
            help="pipeline stage count (layers per stage = n_layer / "
                 "stages)", **lbl)
        self._g_mb = reg.gauge(
            "serve.pp.microbatches",
            help="decode microbatch count the GPipe schedule splits "
                 "each pool step's slot lanes into", **lbl)
        self._g_kv = reg.gauge(
            "serve.pp.kv_bytes_per_stage",
            help="persistent KV-cache bytes each stage holds (its "
                 "L/stages layer slice of every pool this engine "
                 "placed)", **lbl)
        self._c_dispatch = reg.counter(
            "serve.pp.sharded_dispatches",
            help="sharded-twin executions under the pp mesh", **lbl)
        self._c_hops = reg.counter(
            "serve.pp.boundary_hops",
            help="stage-boundary activation hops (one ppermute per "
                 "pipeline tick) the decode twins issued", **lbl)
        self._g_stages.set(self.stages)
        self._g_mb.set(self.microbatches)
        self._g_kv.set(0)
        self._registered = [self._g_stages, self._g_mb, self._g_kv,
                            self._c_dispatch, self._c_hops]
        self._registry = reg
        self._log.info(
            "pp executor up: %d stages (%d layers each) x %d "
            "microbatches over %s", self.stages,
            self.n_layer // self.stages, self.microbatches,
            [str(d) for d in self.mesh.devices.flat])

    # -- placement --------------------------------------------------------
    def place_params(self, params):
        """Stack the per-layer block dicts into (L, ...) arrays
        sharded ``P(pp)`` on the layer axis (each stage materializes
        only its resident layers); embeddings, final norm, and the
        head replicate.  The engine's dispatches carry the stacked
        structure from here on — the host-side step loop never reads
        inside ``params``."""
        out = {k: v for k, v in params.items() if k != "blocks"}
        out["blocks"] = _stack_blocks(params["blocks"])
        spec = {k: (None if v is None else _R)
                for k, v in out.items() if k != "blocks"}
        spec["blocks"] = {k: _LS for k in out["blocks"]}
        self._pspec = spec
        self._key = self._key + (jax.tree.structure(out),)
        return jax.tree.map(
            lambda a, s: jax.device_put(
                a, NamedSharding(self.mesh, s)), out, spec)

    def place_cache(self, tree):
        placed = jax.tree.map(
            lambda a: jax.device_put(a, self._layer_sh), tree)
        self._kv_bytes += sum(
            a.nbytes for a in jax.tree.leaves(tree)) // self.stages
        self._g_kv.set(self._kv_bytes)
        return placed

    def place_replicated(self, tree):
        return jax.tree.map(
            lambda a: jax.device_put(a, self._repl_sh), tree)

    # -- late statics -----------------------------------------------------
    def set_spec(self, spec_k, d_statics):
        raise RuntimeError(
            "speculative decoding on a pipeline engine — check_pp "
            "refuses this at construction")

    def set_chunk(self, chunk_statics):
        self._chunk = dict(chunk_statics)

    def set_window(self, window):
        if window is not None:
            raise RuntimeError(
                "sliding window on a pipeline engine — check_pp "
                "refuses this at construction")
        self._window = None

    # -- twin dispatch ----------------------------------------------------
    def _twin(self, base, extra, make, donate=()):
        key = (base, extra, self._key)
        fn = _TWINS.get(key)
        if fn is None:
            fn = jax.jit(
                jax.shard_map(make(), mesh=self.mesh,
                              in_specs=self._in_specs(base),
                              out_specs=self._out_specs(base),
                              check_vma=False),
                donate_argnums=donate)
            _TWINS[key] = fn
        return fn

    def _dispatch(self, fn, *args, hops=0):
        """Run a twin: the ``serve.pp_boundary`` fault site (an
        injected fault is a raising stage-boundary hop — the engine
        fails typed, the supervisor rebuilds), the dispatch/hop
        counters, and a compile-visibility instant."""
        if _faults._armed:
            _faults.check("serve.pp_boundary")
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        out = fn(*args)
        if before is not None and fn._cache_size() != before:
            _trace.event("serve/compile", cat="serve", fn="serve.pp",
                         stages=self.stages)
        self._c_dispatch.inc()
        if hops:
            self._c_hops.inc(hops)
        return out

    def _in_specs(self, base):
        ps = self._pspec
        return {
            "paged_decode": (ps, _LS, _LS, _R, _R, _R, _R, _R, _R,
                             _R),
            "prefill_one": (ps, _R, _R, _R, _R, _R),
            "prefill_batch": (ps, _R, _R, _R, _R, _R),
            "chunk_row": (ps, _R, _LS, _LS, _R),
            "pool_to_row": (_LS, _LS, _R, _R),
            "row_to_pool": (_LS, _LS, _LS, _LS, _R),
            "rows_to_pool": (_LS, _LS, _LS, _LS, _R, _R),
        }[base]

    def _out_specs(self, base):
        return {
            "paged_decode": (_R, _LS, _LS, _R),
            "prefill_one": (_R, _R, _LS, _LS),
            "prefill_batch": (_R, _R, _LS, _LS),
            "chunk_row": (_R, _LS, _LS),
            "pool_to_row": (_LS, _LS),
            "row_to_pool": (_LS, _LS),
            "rows_to_pool": (_LS, _LS),
        }[base]

    # -- stage helpers (trace-time) --------------------------------------
    def _local_layers(self):
        return self.n_layer // self.stages

    def _fwd_perm(self):
        return [(i, i + 1) for i in range(self.stages - 1)]

    def _stage_wave(self, x, layer_fn):
        """One full pipeline pass of a SINGLE wave (prefill/chunk):
        every rank applies its resident layers to the rotating buffer
        each iteration; rank ``s``'s iteration-``s`` output is the
        true activation, and its per-layer side outputs are kept via
        a rank mask.  Returns (final hidden — masked-psum replicated,
        kept side-output pytree — layer-sharded)."""
        rank = lax.axis_index(PP_AXIS)
        stages = self.stages
        kept = None
        buf = x
        y = x
        for s in range(stages):
            y, side = layer_fn(buf)
            mine = rank == s
            if kept is None:
                kept = jax.tree.map(
                    lambda a: jnp.where(mine, a, jnp.zeros_like(a)),
                    side)
            else:
                kept = jax.tree.map(
                    lambda old, new: jnp.where(mine, new, old),
                    kept, side)
            if stages > 1 and s < stages - 1:
                # no trailing permute: the last wave's output leaves
                # through the masked psum below, so a final hop would
                # be a dead cross-stage transfer (and would break the
                # boundary_hops counter's one-permute-per-issued-hop
                # exactness)
                buf = lax.ppermute(y, PP_AXIS, self._fwd_perm())
        h = jnp.where(rank == stages - 1, y, jnp.zeros_like(y))
        return lax.psum(h, PP_AXIS), kept

    # -- twin bodies ------------------------------------------------------
    def _mk_paged_decode(self, block):
        from ..models import gpt2_decode as G
        from .engine import _select_sample

        st = self._statics
        n_head, eps = st["n_head"], st["eps"]
        moe_top_k = st["moe_top_k"]
        top_k, use_top_p = st["top_k"], st["use_top_p"]
        stages = self.stages
        mb_req = self.microbatches
        L_loc = self._local_layers()
        fwd = self._fwd_perm()

        def body(params, pool_k, pool_v, tables, toks, pos, live,
                 keys, temps, top_p):
            rank = lax.axis_index(PP_AXIS)
            S = toks.shape[0]
            M = math.gcd(mb_req, S)
            mbw = S // M
            blocks = params["blocks"]
            trash = jax.tree.leaves(pool_k)[0].shape[1] - 1
            p_all = jnp.where(live, pos, 0)
            n_blk = jnp.max((p_all + block - 1) // block)
            emb_dt = params["wte"].dtype
            E = params["wte"].shape[1]
            buf = jnp.zeros((mbw, E), emb_dt)
            toks_out = jnp.zeros((S,), jnp.int32)
            keys_out = keys

            def slot_fn(h_r, tbl_r, pc_r):
                x = h_r[None, None, :]
                kbs, vbs = [], []
                for i in range(L_loc):
                    lp = {k: v[i] for k, v in blocks.items()}
                    x, kb, vb = G._block_decode_paged(
                        x, lp, G._cache_layer(pool_k, i),
                        G._cache_layer(pool_v, i), tbl_r, pc_r,
                        n_blk, n_head, eps, block, trash,
                        moe_top_k=moe_top_k)
                    kbs.append(kb)
                    vbs.append(vb)
                return (x[0, 0], G._cache_stack(kbs),
                        G._cache_stack(vbs))

            def samp(lg_r, key, temp):
                ks = jax.random.split(key)
                nxt = _select_sample(lg_r, ks[0], temp, top_k, top_p,
                                     use_top_p)
                return nxt, ks[1]

            for t in range(M + stages - 1):
                m = t - rank
                valid = (m >= 0) & (m < M)
                mc = jnp.clip(m, 0, M - 1)
                i0 = mc * mbw
                tb = lax.dynamic_slice_in_dim(tables, i0, mbw, axis=0)
                tk = lax.dynamic_slice_in_dim(toks, i0, mbw)
                ps_ = lax.dynamic_slice_in_dim(pos, i0, mbw)
                lv = lax.dynamic_slice_in_dim(live, i0, mbw) & valid
                tp_ = lax.dynamic_slice_in_dim(temps, i0, mbw)
                ky = lax.dynamic_slice_in_dim(keys, i0, mbw, axis=0)
                p_c = jnp.where(lv, ps_, 0)
                t_c = jnp.where(lv, tk, 0)
                # pipeline entry (rank 0): embed this tick's
                # microbatch; later stages consume the hop buffer
                x0 = params["wte"][t_c] + params["wpe"][p_c]
                h_in = jnp.where(rank == 0, x0, buf)
                h_out, kb, vb = jax.vmap(
                    slot_fn, in_axes=(0, 0, 0),
                    out_axes=(0, 1, 1))(h_in, tb, p_c)
                # each rank writes ITS layer slice of the touched
                # block per slot; invalid/dead lanes land in trash
                dst = jnp.where(
                    lv, tb[jnp.arange(mbw), p_c // block], trash)
                pool_k = jax.tree.map(
                    lambda p, b: p.at[:, dst].set(b), pool_k, kb)
                pool_v = jax.tree.map(
                    lambda p, b: p.at[:, dst].set(b), pool_v, vb)
                # pipeline exit (rank P-1): final LN + head + sample
                # for the microbatch that just left the last stage.
                # Every rank traces this (SPMD), only the last one's
                # values survive the masked writes below.
                xf = G._ln(h_out[:, None, :], params["lnf_s"],
                           params["lnf_b"], eps)
                lg = G._logits(xf, params)[:, 0]
                nxt, k2 = jax.vmap(samp)(lg, ky, tp_)
                emit = (rank == stages - 1) & valid
                cur_t = lax.dynamic_slice_in_dim(toks_out, i0, mbw)
                toks_out = lax.dynamic_update_slice_in_dim(
                    toks_out, jnp.where(emit, nxt, cur_t), i0, axis=0)
                cur_k = lax.dynamic_slice_in_dim(keys_out, i0, mbw,
                                                 axis=0)
                keys_out = lax.dynamic_update_slice_in_dim(
                    keys_out, jnp.where(emit, k2, cur_k), i0, axis=0)
                if stages > 1 and t < M + stages - 2:
                    # the final tick's output leaves through the
                    # masked psums below — same dead-hop guard as
                    # _stage_wave, keeping issued permutes ==
                    # M + stages - 2 == the boundary_hops count
                    buf = lax.ppermute(h_out, PP_AXIS, fwd)
            last = rank == stages - 1
            toks_out = lax.psum(
                jnp.where(last, toks_out, jnp.zeros_like(toks_out)),
                PP_AXIS)
            keys_out = lax.psum(
                jnp.where(last, keys_out, jnp.zeros_like(keys_out)),
                PP_AXIS)
            return toks_out, pool_k, pool_v, keys_out

        return body

    def _prefill_wave(self, params, x):
        """Shared stage-flow prefill core: run the batch ``x``
        (B, W, E) through every stage, each rank keeping its resident
        layers' head-shaped (and optionally quantized) K/V.  Returns
        (final-LN hidden (B, W, E) replicated, kc, vc layer-sharded
        (L_loc, B, H, W, D))."""
        from ..models import gpt2_decode as G

        st = self._statics
        n_head, eps = st["n_head"], st["eps"]
        moe_top_k = st["moe_top_k"]
        quant = self._quant
        L_loc = self._local_layers()
        blocks = params["blocks"]
        b, sp, e = x.shape
        d = e // n_head

        def layer_fn(h):
            y = h
            ks, vs = [], []
            for i in range(L_loc):
                lp = {k: v[i] for k, v in blocks.items()}
                y, k_, v_ = G._block_prefill(y, lp, n_head, eps,
                                             moe_top_k=moe_top_k)
                n_kv = k_.shape[-1] // d
                kh = k_.reshape(b, sp, n_kv, d).transpose(0, 2, 1, 3)
                vh = v_.reshape(b, sp, n_kv, d).transpose(0, 2, 1, 3)
                if quant:
                    kh = G._quantize_kv(kh)
                    vh = G._quantize_kv(vh)
                ks.append(kh)
                vs.append(vh)
            return y, (G._cache_stack(ks), G._cache_stack(vs))

        h, (kc, vc) = self._stage_wave(x, layer_fn)
        h = G._ln(h, params["lnf_s"], params["lnf_b"], eps)
        return h, kc, vc

    def _mk_prefill_one(self):
        from ..models import gpt2_decode as G
        from .engine import _select_sample

        st = self._statics
        top_k, use_top_p = st["top_k"], st["use_top_p"]
        wave = self._prefill_wave

        def body(params, ids, prompt_len, key, temp, top_p):
            pos = jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
            x = jnp.take(params["wte"], ids, axis=0) + \
                jnp.take(params["wpe"], pos, axis=0)
            hidden, kc, vc = wave(params, x)
            last_h = jax.lax.dynamic_index_in_dim(
                hidden, prompt_len - 1, axis=1, keepdims=False)
            logit0 = G._logits(last_h[:, None, :], params)[0, 0]
            ks = jax.random.split(key)
            tok0 = _select_sample(logit0, ks[0], temp, top_k, top_p,
                                  use_top_p)
            return tok0, ks[1], kc, vc

        return body

    def _mk_prefill_batch(self):
        from ..models import gpt2_decode as G
        from .engine import _select_sample

        st = self._statics
        top_k, use_top_p = st["top_k"], st["use_top_p"]
        wave = self._prefill_wave

        def body(params, ids, plens, seeds, temps, top_p):
            pos = jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
            x = jnp.take(params["wte"], ids, axis=0) + \
                jnp.take(params["wpe"], pos, axis=0)
            hidden, kc, vc = wave(params, x)

            def tail(h_r, plen, seed, temp):
                key0 = jax.random.split(jax.random.PRNGKey(seed),
                                        1)[0]
                last_h = jax.lax.dynamic_index_in_dim(
                    h_r, plen - 1, axis=0, keepdims=False)
                logit0 = G._logits(last_h[None, None, :],
                                   params)[0, 0]
                ks = jax.random.split(key0)
                tok0 = _select_sample(logit0, ks[0], temp, top_k,
                                      top_p, use_top_p)
                return tok0, ks[1]

            tok0, keys = jax.vmap(tail)(hidden, plens, seeds, temps)
            return tok0, keys, kc, vc

        return body

    def _mk_chunk_row(self):
        from ..models import gpt2_decode as G

        ck = dict(self._chunk)
        n_head, eps = ck["n_head"], ck["eps"]
        moe_top_k, chunk = ck["moe_top_k"], ck["chunk"]
        L_loc = self._local_layers()
        stage_wave = self._stage_wave

        def body(params, ids, kc_row, vc_row, off):
            blocks = params["blocks"]
            toks = jax.lax.dynamic_slice(ids, (0, off), (1, chunk))
            pos = off + jnp.arange(chunk)
            x = jnp.take(params["wte"], toks[0], axis=0)[None] + \
                jnp.take(params["wpe"], pos, axis=0)[None]

            # the SAME wave schedule prefill rides (_stage_wave: one
            # schedule definition, no drift): each rank advances the
            # chunk through its resident layers against its ORIGINAL
            # row slice — rank r's true wave is wave r, and at that
            # point no earlier where-fold has touched rank r's local
            # rows, so reading the closure rows is exact — and keeps
            # its own updated (kc, vc) stacks via the rank mask
            def layer_fn(h):
                y = h
                new_k, new_v = [], []
                for i in range(L_loc):
                    lp = {k: v[i] for k, v in blocks.items()}
                    y, kl, vl = G._block_chunk(
                        y, lp, G._cache_layer(kc_row, i),
                        G._cache_layer(vc_row, i), off, n_head, eps,
                        moe_top_k=moe_top_k)
                    new_k.append(kl)
                    new_v.append(vl)
                return y, (G._cache_stack(new_k),
                           G._cache_stack(new_v))

            h, (kc2, vc2) = stage_wave(x, layer_fn)
            h = G._ln(h, params["lnf_s"], params["lnf_b"], eps)
            return h, kc2, vc2

        return body

    # -- the executor surface (paged subset — check_pp guarantees it) -----
    def paged_decode_step(self, params, pool_k, pool_v, tables, toks,
                          pos, live, keys, temps, top_p, block,
                          kernel="block"):
        fn = self._twin("paged_decode", (block,),
                        lambda: self._mk_paged_decode(block),
                        donate=(1, 2))
        S = int(toks.shape[0])
        hops = math.gcd(self.microbatches, S) + self.stages - 2
        return self._dispatch(fn, params, pool_k, pool_v, tables,
                              toks, pos, live, keys, temps, top_p,
                              hops=max(hops, 0))

    def paged_spec_step(self, *a, **k):
        raise RuntimeError(
            "speculative decoding on a pipeline engine — check_pp "
            "refuses this at construction")

    def pool_decode_step(self, *a, **k):
        raise RuntimeError(
            "slot-arena decode on a pipeline engine — pp requires "
            "paged= (check_pp refuses this at construction)")

    pool_spec_step = paged_spec_step

    def prefill_one(self, params, ids, prompt_len, key, temp, top_p):
        fn = self._twin("prefill_one", (), self._mk_prefill_one)
        return self._dispatch(fn, params, ids, prompt_len, key, temp,
                              top_p, hops=self.stages - 1)

    def prefill_batch(self, params, ids, plens, seeds, temps, top_p):
        fn = self._twin("prefill_batch", (), self._mk_prefill_batch)
        return self._dispatch(fn, params, ids, plens, seeds, temps,
                              top_p, hops=self.stages - 1)

    def chunk_row(self, params, ids, kc_row, vc_row, off):
        fn = self._twin("chunk_row",
                        tuple(sorted(self._chunk.items())),
                        self._mk_chunk_row, donate=(2, 3))
        return self._dispatch(fn, params, ids, kc_row, vc_row, off,
                              hops=self.stages - 1)

    def write_slot(self, *a, **k):
        raise RuntimeError(
            "slot-arena write on a pipeline engine — pp requires "
            "paged= (check_pp refuses this at construction)")

    read_slot = write_slot

    def pool_to_row(self, pool_k, pool_v, idx, n_used):
        from .tp import _pool_to_row_body

        fn = self._twin("pool_to_row", (), lambda: _pool_to_row_body)
        return self._dispatch(fn, pool_k, pool_v, idx, n_used)

    def row_to_pool(self, pool_k, pool_v, kc_row, vc_row, idx):
        from .tp import _row_to_pool_body

        fn = self._twin("row_to_pool", (), lambda: _row_to_pool_body,
                        donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_row, vc_row, idx)

    def rows_to_pool(self, pool_k, pool_v, kc_rows, vc_rows, sel, idx):
        from .tp import _rows_to_pool_body

        fn = self._twin("rows_to_pool", (),
                        lambda: _rows_to_pool_body, donate=(0, 1))
        return self._dispatch(fn, pool_k, pool_v, kc_rows, vc_rows,
                              sel, idx)

    # -- lifecycle / reporting -------------------------------------------
    def unregister(self):
        """Release the registry entries (engine close()); the twin
        cache stays module-wide by design."""
        self._registry.remove(*self._registered)

    def snapshot(self) -> dict:
        return {
            "stages": self.stages,
            "layers_per_stage": self.n_layer // self.stages,
            "microbatches": self.microbatches,
            "devices": [str(d) for d in self.mesh.devices.flat],
            "kv_bytes_per_stage": self._kv_bytes,
            "sharded_dispatches": self._c_dispatch.value,
            "boundary_hops": self._c_hops.value,
        }


class _BlockKernelSentinel:
    """Stands in for a PagedConfig in the defensive re-validation
    path (the engine already validated the REAL paged config before
    construction)."""

    kernel = "block"
