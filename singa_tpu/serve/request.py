"""Request/result surface of the serving engine.

A :class:`GenerationRequest` is what callers submit; the engine hands
back a :class:`RequestHandle` immediately (admission is asynchronous —
the request sits in the scheduler queue until a slot frees).  Results
arrive as :class:`GenerationResult` on the handle once the row retires;
streaming consumers pass ``on_token`` and receive every token the
moment the engine emits it (the prefill token included).

Rejections are DISTINCT error types so callers can tell back-pressure
(:class:`QueueFullError` — retry later, shed load) from staleness
(:class:`DeadlineExceededError` — the answer is no longer wanted) —
the two need opposite client reactions.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

_req_counter = itertools.count()


class QueueFullError(RuntimeError):
    """Admission control: the scheduler queue is at max_queue_depth.
    Raised synchronously by ``submit`` — the request was never
    accepted, so there is no handle to poll."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before a slot could run it.  The
    scheduler drops it at schedule time; the handle's ``result()``
    re-raises this."""


class EngineFailedError(RuntimeError):
    """The engine's decode/prefill path raised and the engine failed
    itself rather than wedging: every in-flight and queued request is
    rejected with one of these (no handle is ever left dangling).
    ``started`` distinguishes requests that were occupying a slot
    (tokens may have streamed — NOT safely re-runnable through
    ``on_token``) from queued ones that never started (safely
    requeued by :class:`~singa_tpu.serve.supervisor.EngineSupervisor`).
    """

    def __init__(self, message, request_id=None, started=None,
                 engine_step=None):
        super().__init__(message)
        self.request_id = request_id
        self.started = started
        self.engine_step = engine_step


class RestartBudgetExceededError(EngineFailedError):
    """The supervisor's restart budget is spent; remaining requests
    are rejected with this instead of being requeued into an engine
    that keeps dying."""


class FleetDownError(EngineFailedError):
    """Every replica in a :class:`~singa_tpu.serve.fleet.ServeFleet`
    is unhealthy: there is no sibling left to fail over to.  Raised by
    ``ServeFleet.submit`` for new arrivals; outstanding never-started
    requests of the last replica are rejected with it (``started=False``
    — safe to resubmit once a replica is revived)."""


class LoadShedError(RuntimeError):
    """The request was shed by SLO-pressure admission control (queue
    beyond ``SLO.queue_depth_max``): either a lower-priority queued
    request evicted in favor of a newer higher-priority one, or an
    incoming request refused while the queue is saturated.  Distinct
    from :class:`QueueFullError` (hard back-pressure bound) — shedding
    is a POLICY choice made before latency collapses, and clients
    should drop, not retry immediately."""


@dataclass
class GenerationRequest:
    """One generation job.

    ``prompt_ids``: 1-D int token ids.  ``temperature <= 0`` is greedy
    decoding; otherwise ``seed`` keys the request's private sampling
    chain — the SAME chain single-prompt ``generate`` derives from its
    seed, which is what makes engine output token-identical to the
    offline path (tests/test_serve.py).  ``deadline`` is an absolute
    time on the engine's clock (default ``time.monotonic``); a request
    still queued past it is rejected, never silently served late.
    ``on_token(request, token)`` streams each emitted token.
    ``priority`` only matters under SLO-pressure load shedding (higher
    wins; default 0) — FIFO admission order is unchanged by it.
    ``pin_session``: on an engine with a prefix cache, retire pins the
    full sequence (prompt + generation) in the radix tree and attaches
    a :class:`~singa_tpu.serve.prefix.SessionHandle` to the result, so
    the next turn's re-sent conversation is a block-prefix hit; without
    a cache the handle is still attached (continuation just runs
    cold).
    ``stop_token``: optional end-of-sequence token id — the request
    retires the moment it emits it (``finish_reason="stop"``), the
    engine's analog of EOS for callers whose tokenizer has one.  With
    a speculative engine the check runs per accepted token, so a
    multi-token chunk stops MID-chunk and the surplus accepted tokens
    are never emitted.
    ``session_of``: the :class:`SessionHandle` this request continues
    (set automatically by ``SessionHandle.request``).  A single engine
    ignores it; the fleet router uses it for STICKY routing — the
    continuation lands on the replica whose prefix cache holds the
    pinned session, so session KV reuse stays replica-local (any other
    replica would serve it cold but correct).
    ``n``: parallel-sampling width (the fork round).  ``n > 1`` admits
    ONE prompt and decodes n branches that share every prompt block in
    the paged pool copy-on-first-write (serve/fork.py) — branch 0 is
    the exact stream ``n=1`` would produce, branches 1..n-1 re-key via
    ``fold_in(key, branch)``.  Paged engines only; incompatible with
    ``pin_session`` (a session pins ONE continuation) and requires
    ``max_new_tokens >= 2`` (branches share the first token and
    diverge after it).
    ``structured``: a token automaton (``serve.structured`` —
    ``JsonSchemaAutomaton`` or anything with
    ``initial``/``mask``/``advance``/``done``) constraining every
    emitted token to the grammar: the engine applies its per-state
    vocab mask inside the jitted sample executable and retires the
    request the moment the automaton completes."""

    prompt_ids: np.ndarray
    max_new_tokens: int = 20
    temperature: float = 0.0
    seed: int = 0
    deadline: Optional[float] = None
    on_token: Optional[Callable] = None
    priority: int = 0
    pin_session: bool = False
    session_of: Optional[object] = None
    stop_token: Optional[int] = None
    n: int = 1
    structured: Optional[object] = None
    request_id: str = field(
        default_factory=lambda: f"req-{next(_req_counter)}")

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids,
                                     np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("prompt_ids must be non-empty")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
                " (a serve request that generates nothing is a no-op)")
        if self.stop_token is not None:
            self.stop_token = int(self.stop_token)
        self.n = int(self.n)
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if self.n > 1 and self.pin_session:
            raise ValueError(
                f"n={self.n} with pin_session: a pinned session "
                "continues ONE stream — fork the continuation instead "
                "(submit n=1 with pin_session, then fork() the handle)")
        if self.n > 1 and self.max_new_tokens < 2:
            raise ValueError(
                f"n={self.n} with max_new_tokens="
                f"{self.max_new_tokens}: branches share the prompt AND "
                "the first sampled token, so a 1-token request has "
                "nothing to diverge on — all n streams would be "
                "identical; raise max_new_tokens or drop n")
        if self.structured is not None:
            for attr in ("initial", "mask", "advance", "done"):
                if not callable(getattr(self.structured, attr, None)):
                    raise ValueError(
                        f"structured= must be a token automaton with "
                        f"initial()/mask()/advance()/done() (see "
                        f"serve.structured.JsonSchemaAutomaton); "
                        f"{type(self.structured).__name__} has no "
                        f"callable {attr!r}")


@dataclass
class GenerationResult:
    """Terminal state of a request.  ``tokens`` is prompt +
    continuation (the exact array single-prompt ``generate`` would
    return); ``finish_reason`` is ``"length"`` for a spent token
    budget, ``"stop"`` when the request's ``stop_token`` ended it
    early, or ``"pruned"`` when a forked branch was cut by ``prune()``
    (the fork round — a pruned branch still seals a complete result).
    Latency fields are on the engine clock: ``ttft`` measures submit →
    first token, ``tpot`` the mean inter-token time after it."""

    request_id: str
    tokens: np.ndarray
    finish_reason: str
    ttft: float
    tpot: Optional[float]
    queue_time: float
    admitted_step: int
    finished_step: int
    # set when the request asked pin_session=True: the multi-turn
    # continuation handle (serve/prefix.py SessionHandle)
    session: Optional[object] = None
    # fork round: which branch of a fork group produced this result
    # (0 for plain requests) and its cumulative chosen-token logprob
    # under the RAW model distribution — the best-of-n ranking signal
    # (None outside a fork group; the shared first token scores 0.0)
    branch: int = 0
    score: Optional[float] = None


class RequestHandle:
    """Caller-side view of a submitted request.  ``done()`` flips when
    the engine retires or rejects the row; ``result()`` returns the
    :class:`GenerationResult` or re-raises the rejection error."""

    def __init__(self, request: GenerationRequest):
        self.request = request
        self._result: Optional[GenerationResult] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._result is not None or self._error is not None

    def result(self) -> GenerationResult:
        if self._error is not None:
            raise self._error
        if self._result is None:
            raise RuntimeError(
                f"{self.request.request_id} not finished; drive the "
                "engine (step()/run_until_complete()) first")
        return self._result

    # engine-side completion hooks
    def _finish(self, result: GenerationResult):
        self._result = result

    def _reject(self, error: BaseException):
        self._error = error
