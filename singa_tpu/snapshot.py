"""``Snapshot`` key->tensor store (reference: ``src/io/snapshot.cc`` +
``python/singa/snapshot.py``, unverified — SURVEY.md §3.5): the low-level
checkpoint container under ``Model.save_states``.

Storage is the native BinFile record store (native/singa_io.cpp via
io/binfile.py) — each record is a small numpy header + raw buffer.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from . import tensor
from .io.binfile import BinFileReader, BinFileWriter
from .observe import trace as _trace
from .resilience import faults as _faults
from .tensor import Tensor


def _encode(arr: np.ndarray) -> bytes:
    meta = json.dumps({"dtype": str(arr.dtype),
                       "shape": list(arr.shape)}).encode()
    return struct.pack("<I", len(meta)) + meta + \
        np.ascontiguousarray(arr).tobytes()


def _decode(blob: bytes) -> np.ndarray:
    (mlen,) = struct.unpack("<I", blob[:4])
    meta = json.loads(blob[4:4 + mlen].decode())
    return np.frombuffer(blob[4 + mlen:], dtype=meta["dtype"]).reshape(
        meta["shape"]).copy()


class Snapshot:
    """API parity with the reference: ``Snapshot(path, mode)`` where mode
    is Snapshot.kWrite / Snapshot.kRead; ``write(key, tensor)``,
    ``read()`` -> {key: Tensor}."""

    kRead = 0
    kWrite = 1

    def __init__(self, path, mode=1, buffer_size=None, max_param_size=None):
        self.path = path if path.endswith(".bin") else path + ".bin"
        self.mode = mode
        if mode == Snapshot.kWrite:
            self._writer = BinFileWriter(self.path)
            self._reader = None
        else:
            self._reader = BinFileReader(self.path)
            self._writer = None

    def write(self, key, t):
        assert self._writer is not None, "snapshot opened for reading"
        if _faults._armed:
            _faults.check("checkpoint.write")
        arr = tensor.to_numpy(t) if isinstance(t, Tensor) else np.asarray(t)
        with _trace.span("snapshot/write_record", cat="snapshot",
                         key=str(key), bytes=int(arr.nbytes)):
            self._writer.put(key, _encode(arr))

    # reference alias
    Write = write

    def read(self) -> dict:
        assert self._reader is not None, "snapshot opened for writing"
        if _faults._armed:
            _faults.check("checkpoint.read")
        with _trace.span("snapshot/read", cat="snapshot",
                         path=self.path):
            return {k: tensor.from_numpy(_decode(v))
                    for k, v in self._reader.items()}

    Read = read

    def read_numpy(self) -> dict:
        assert self._reader is not None
        return {k: _decode(v) for k, v in self._reader.items()}

    def done(self):
        if self._writer:
            self._writer.close()
            self._writer = None
        if self._reader:
            self._reader.close()
            self._reader = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.done()
