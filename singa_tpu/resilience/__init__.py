"""singa_tpu.resilience — surviving the failures the observe layer can
only watch (PR 4).

Four cooperating pieces:

* ``faults.py`` — a process-wide registry of named fault-injection
  sites threaded through the checkpoint, io, collective, and serving
  hot paths.  Disarmed (the default) every site is a single module-flag
  check; armed, seeded-deterministic policies (fail-once, fail-rate,
  fail-after-N, latency) raise :class:`FaultInjected` exactly where a
  real fault would surface.  Chaos tests and the CI chaos job drive
  the whole recovery stack through these sites.
* ``retry.py`` — exponential backoff + jitter with retry budgets and
  transient/fatal error classification.  Every retry and every
  give-up is counted in the observe registry
  (``resilience.retries{site=}`` / ``resilience.gave_up{site=}``).
* ``checkpoint.py`` — :class:`CheckpointManager`: step-numbered
  checkpoint directories with a strict-JSON manifest (whole-file
  digest + step/param metadata), last-K retention with atomic
  rotation, and :meth:`CheckpointManager.restore_latest` that
  validates the newest checkpoint and falls back to the previous good
  one on corruption (``resilience.checkpoint_fallbacks``).
* ``serve.supervisor`` (in the serve package) — rebuilds a failed
  engine, requeues not-yet-started requests, enforces a restart
  budget, and sheds lowest-priority queued work under SLO pressure.

Everything reports into ``observe.health_report()`` under the
``resilience`` section.  See docs/RESILIENCE.md.
"""

from . import faults  # noqa: F401
from . import retry  # noqa: F401
from .checkpoint import (CheckpointCorruptError,  # noqa: F401
                         CheckpointManager, NoValidCheckpointError)
from .faults import (FaultInjected, FailAfterN, FailOnce,  # noqa: F401
                     FailRate, Latency, clear, inject, injected)
from .retry import (RetryBudgetExceededError, RetryPolicy,  # noqa: F401
                    is_transient, retry_call, retryable)
