"""CheckpointManager: step-numbered checkpoint directories with a
strict-JSON manifest, last-K retention, and corruption fallback.

Layout (one directory per step under ``root``)::

    root/
      step_00000010/
        states.zip      # Model.save_states zip (one .npy per tensor)
        manifest.json   # strict JSON, written LAST (the commit record)
      step_00000020/
        ...

The manifest carries a whole-file sha256 + byte size per data file
(BinFile already CRCs per record; the digest catches truncation and
cross-file swaps too), the step number, and param metadata.  Writes
are atomic at two levels: ``Model.save_states`` already writes
zip-to-temp + ``os.replace``, and the manager stages the whole step
directory under a dot-prefixed temp name and renames it into place
only after the manifest is fsynced — a crash mid-checkpoint leaves a
temp directory ``restore_latest`` never looks at, not a half-valid
step.

``restore_latest`` walks steps newest→oldest, validating each
(manifest parses as strict JSON, files exist, sizes and digests
match) before loading; a corrupt or truncated newest checkpoint
increments ``resilience.checkpoint_fallbacks`` and falls back to the
previous good one.  Transient I/O during write/read goes through the
retry layer (``resilience.retries{site=checkpoint.write|read}``);
corruption is classified fatal so it falls through to the fallback
walk instead of burning the retry budget.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid

import numpy as np

from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..utils.logging import get_channel
from . import faults as _faults
from .retry import RetryPolicy, retry_call

__all__ = ["CheckpointManager", "CheckpointCorruptError",
           "NoValidCheckpointError", "MANIFEST_NAME", "STATES_NAME"]

MANIFEST_NAME = "manifest.json"
STATES_NAME = "states.zip"
_SCHEMA = "singa_tpu.checkpoint/1"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed validation (bad manifest, missing
    file, size or digest mismatch).  Fatal to the retry layer — a
    digest mismatch never heals — but absorbed by the
    ``restore_latest`` fallback walk."""

    def __init__(self, path, reason):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


class NoValidCheckpointError(RuntimeError):
    """Every step directory under the root failed validation (or the
    root holds none)."""


def _sha256(path, chunk=1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


class CheckpointManager:
    """Manage step-numbered checkpoints of one model under ``root``.

    >>> mgr = CheckpointManager("/ckpt/run0", keep=3)
    >>> mgr.save(model, step=100)
    >>> step, aux = mgr.restore_latest(model)   # falls back on corruption

    ``keep``: last-K retention — older step directories are deleted
    after each successful save (K >= 2 is what makes the corruption
    fallback useful; K=1 keeps only the copy that might be the corrupt
    one).  ``retry_policy``: backoff for transient write/read I/O.
    """

    def __init__(self, root, keep=3, retry_policy=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.root = str(root)
        self.keep = int(keep)
        self.retry_policy = (retry_policy if retry_policy is not None
                             else RetryPolicy(base_delay_s=0.02,
                                              max_delay_s=0.5))
        os.makedirs(self.root, exist_ok=True)
        self._log = get_channel("resilience")
        # sweep crash-orphaned staging/aside directories (dot-prefixed
        # — a preemption mid-save leaves one behind with a full-sized
        # states.zip inside; without this, a preemption-heavy fleet
        # leaks a model-sized orphan per crash until the disk fills).
        # Done at construction only: this manager has no in-flight
        # saves yet, so anything dot-prefixed here is dead.
        for name in os.listdir(self.root):
            if name.startswith(".step_"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
                self._log.warning(
                    "swept orphaned checkpoint staging dir %s "
                    "(crash mid-save?)", name)
        reg = _registry()
        self._c_saves = reg.counter(
            "resilience.checkpoint_saves",
            help="checkpoint step directories committed")
        self._c_fallbacks = reg.counter(
            "resilience.checkpoint_fallbacks",
            help="restore_latest skips of a corrupt/unreadable step")

    # -- layout ----------------------------------------------------------
    @staticmethod
    def _dirname(step) -> str:
        return f"step_{int(step):08d}"

    def step_dir(self, step) -> str:
        return os.path.join(self.root, self._dirname(step))

    def steps(self) -> list:
        """Committed step numbers, ascending.  Temp (dot-prefixed) and
        foreign directories are ignored."""
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step_") and not name.startswith("."):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self):
        s = self.steps()
        return s[-1] if s else None

    # -- save ------------------------------------------------------------
    def save(self, model, step, aux_states=None) -> str:
        """Write one checkpoint for ``step`` and rotate retention.
        Returns the committed step directory.  Transient write errors
        retry with backoff (``resilience.retries{site=checkpoint.write}``);
        an existing directory for the same step is swapped out via
        rename-aside + rename-in (old copy deleted last).
        """
        final = self.step_dir(step)
        tmp = os.path.join(self.root,
                           f".{self._dirname(step)}.{uuid.uuid4().hex}")

        def _write():
            _faults.check("checkpoint.write")
            os.makedirs(tmp, exist_ok=True)
            states = os.path.join(tmp, STATES_NAME)
            model.save_states(states, aux_states=aux_states)
            st = model.get_states()
            manifest = {
                "schema": _SCHEMA,
                "step": int(step),
                "created_unix_s": time.time(),
                "param_count": int(sum(
                    int(np.prod(t.shape)) if t.shape else 1
                    for t in st.values())),
                "tensor_count": len(st),
                "files": {
                    STATES_NAME: {
                        "bytes": os.path.getsize(states),
                        "sha256": _sha256(states),
                    },
                },
            }
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                # allow_nan=False: the manifest is the STRICT-JSON
                # commit record CI and tooling parse with
                # parse_constant=raise
                json.dump(manifest, f, indent=1, allow_nan=False)
                f.flush()
                os.fsync(f.fileno())
            return manifest

        with _trace.span("resilience/checkpoint_save", cat="resilience",
                         step=int(step), path=final):
            try:
                manifest = retry_call(_write, "checkpoint.write",
                                      policy=self.retry_policy)
                # replace an existing same-step directory by renaming
                # it aside (dot-prefixed — steps() never sees it),
                # renaming the new one in, and only then deleting the
                # old.  The no-copy-visible window is two renames, not
                # a size-proportional rmtree; a crash inside it still
                # degrades to restore_latest's fallback to the
                # previous retained step, never to silent corruption.
                old = None
                if os.path.isdir(final):
                    old = os.path.join(
                        self.root, f".{self._dirname(step)}.old."
                                   f"{uuid.uuid4().hex}")
                    os.rename(final, old)
                os.rename(tmp, final)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        self._c_saves.inc()
        self._log.info("checkpoint committed: step=%d -> %s "
                       "(%d params)", step, final,
                       manifest["param_count"])
        self._retain()
        return final

    def _retain(self):
        """Drop the oldest committed steps beyond ``keep``.  Runs after
        a successful commit, so the new checkpoint is never traded for
        the old one it was meant to replace."""
        steps = self.steps()
        for step in steps[:-self.keep]:
            path = self.step_dir(step)
            shutil.rmtree(path, ignore_errors=True)
            self._log.info("checkpoint retention: dropped step %d", step)

    # -- validate / restore ----------------------------------------------
    def validate(self, step) -> dict:
        """Validate one committed step; returns its manifest or raises
        :class:`CheckpointCorruptError` naming what failed."""
        path = self.step_dir(step)
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(mpath):
            raise CheckpointCorruptError(path, "manifest.json missing")
        try:
            with open(mpath) as f:
                manifest = json.load(
                    f, parse_constant=lambda c: (_ for _ in ()).throw(
                        ValueError(f"non-strict JSON constant {c}")))
        except (ValueError, OSError) as e:
            raise CheckpointCorruptError(
                path, f"manifest unreadable: {e!r}") from e
        if manifest.get("schema") != _SCHEMA:
            raise CheckpointCorruptError(
                path, f"unknown schema {manifest.get('schema')!r}")
        if manifest.get("step") != int(step):
            raise CheckpointCorruptError(
                path, f"manifest step {manifest.get('step')} != "
                      f"directory step {step}")
        for name, meta in manifest.get("files", {}).items():
            fpath = os.path.join(path, name)
            if not os.path.isfile(fpath):
                raise CheckpointCorruptError(path, f"{name} missing")
            size = os.path.getsize(fpath)
            if size != meta.get("bytes"):
                raise CheckpointCorruptError(
                    path, f"{name} truncated: {size} bytes, manifest "
                          f"says {meta.get('bytes')}")
            digest = _sha256(fpath)
            if digest != meta.get("sha256"):
                raise CheckpointCorruptError(
                    path, f"{name} digest mismatch: {digest[:12]}... "
                          f"!= manifest {str(meta.get('sha256'))[:12]}...")
        return manifest

    def restore_latest(self, model):
        """Load the newest VALID checkpoint into ``model``.  Returns
        ``(step, aux_states)``.  A corrupt/truncated/unreadable step
        increments ``resilience.checkpoint_fallbacks`` and falls back
        to the previous one; raises :class:`NoValidCheckpointError`
        when none survive."""
        steps = self.steps()
        for step in reversed(steps):
            path = self.step_dir(step)
            try:
                self.validate(step)

                def _read():
                    _faults.check("checkpoint.read")
                    return model.load_states(
                        os.path.join(path, STATES_NAME))

                with _trace.span("resilience/checkpoint_restore",
                                 cat="resilience", step=int(step)):
                    aux = retry_call(_read, "checkpoint.read",
                                     policy=self.retry_policy)
                self._log.info("restored checkpoint step=%d from %s",
                               step, path)
                return step, aux
            except Exception as e:
                # CheckpointCorruptError, zipfile.BadZipFile,
                # truncated-read OSError, retry give-up, state-shape
                # mismatch: all mean "this step cannot serve a
                # restore" — record the fallback and walk back
                self._c_fallbacks.inc()
                _trace.event("resilience/checkpoint_fallback",
                             cat="resilience", step=int(step),
                             error=repr(e))
                self._log.error(
                    "checkpoint step %d unusable (%r); falling back "
                    "to previous", step, e)
        raise NoValidCheckpointError(
            f"no valid checkpoint under {self.root} "
            f"(tried steps {list(reversed(steps))})")
