"""Retry policies: exponential backoff + jitter with retry budgets and
transient/fatal error classification.

The classification is the load-bearing part: retrying a corrupted
checkpoint read wastes the fallback window, and NOT retrying a flaky
NFS write kills a run a 50 ms sleep would have saved.  The default
:func:`is_transient` treats OS-level I/O errors (``OSError`` and
subclasses — ``ConnectionError``, ``TimeoutError``'s OS variant),
``TimeoutError``, and transient :class:`~.faults.FaultInjected` as
retryable; everything else — corruption errors, value errors,
programming bugs — is fatal and re-raised on the first attempt.
Callers can extend the transient set per call.

Accounting: each re-attempt increments ``resilience.retries{site=}``
and each exhausted budget ``resilience.gave_up{site=}`` in the observe
registry, so ``health_report()["resilience"]`` shows where the fleet
is limping.  Backoff jitter draws from a seeded RNG (deterministic
tests); ``sleep`` is injectable for the same reason.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..utils.logging import get_channel
from .faults import FaultInjected

__all__ = ["RetryPolicy", "RetryBudgetExceededError", "is_transient",
           "retry_call", "retryable", "DEFAULT_POLICY"]


class RetryBudgetExceededError(RuntimeError):
    """Every attempt of a retryable operation failed transiently.  The
    last underlying error is chained as ``__cause__``; ``site`` and
    ``attempts`` say where and how hard we tried."""

    def __init__(self, site, attempts, last_error):
        super().__init__(
            f"{site}: gave up after {attempts} attempts "
            f"(last error: {last_error!r})")
        self.site = site
        self.attempts = attempts
        self.last_error = last_error


def is_transient(exc, extra_types=()) -> bool:
    """Default transient/fatal split.  Injected faults carry their own
    classification; ``CorruptRecordError`` is an OSError subclass but
    corruption never heals on retry, so it is explicitly fatal."""
    from ..io.binfile import CorruptRecordError

    if isinstance(exc, FaultInjected):
        return exc.transient
    if isinstance(exc, CorruptRecordError):
        return False
    if extra_types and isinstance(exc, tuple(extra_types)):
        return True
    return isinstance(exc, (OSError, TimeoutError))


@dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` total tries (1 = no retry).  Delay before
    re-attempt k (0-based) is ``min(base * 2**k, max) * (1 + jitter *
    U[0,1))`` with U drawn from ``random.Random(seed)``.

    ``seed=None`` (the default) seeds from OS entropy per call, so N
    processes hitting the same shared-dependency failure at the same
    step retry at DECORRELATED instants — the thundering-herd breakup
    jitter exists for.  Pass an explicit seed for deterministic
    backoff sequences in tests."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5
    seed: int | None = None

    def delay(self, attempt, rng) -> float:
        d = min(self.base_delay_s * (2 ** attempt), self.max_delay_s)
        return d * (1.0 + self.jitter * rng.random())


DEFAULT_POLICY = RetryPolicy()


def retry_call(fn, site, policy=None, classify=is_transient,
               sleep=time.sleep, reg=None):
    """Run ``fn()`` under ``policy``.  Fatal errors re-raise
    immediately; transient ones back off and retry until the budget is
    spent, then raise :class:`RetryBudgetExceededError` chained to the
    last error."""
    policy = policy if policy is not None else DEFAULT_POLICY
    reg = reg if reg is not None else _registry()
    rng = random.Random(policy.seed)
    log = get_channel("resilience")
    last = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except Exception as e:
            if not classify(e):
                raise
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            reg.counter(
                "resilience.retries",
                help="transient failures retried with backoff",
                site=site).inc()
            d = policy.delay(attempt, rng)
            _trace.event("resilience/retry", cat="resilience",
                         site=site, attempt=attempt + 1,
                         delay_s=round(d, 4), error=repr(e))
            log.warning("%s: transient failure (attempt %d/%d), "
                        "retrying in %.3fs: %r", site, attempt + 1,
                        policy.max_attempts, d, e)
            sleep(d)
    reg.counter(
        "resilience.gave_up",
        help="retry budgets exhausted (operation failed for good)",
        site=site).inc()
    _trace.event("resilience/gave_up", cat="resilience", site=site,
                 attempts=policy.max_attempts, error=repr(last))
    log.error("%s: retry budget exhausted after %d attempts: %r",
              site, policy.max_attempts, last)
    raise RetryBudgetExceededError(site, policy.max_attempts,
                                   last) from last


def retryable(site, policy=None, classify=is_transient,
              sleep=time.sleep):
    """Decorator form of :func:`retry_call`."""
    def deco(fn):
        def wrapper(*a, **kw):
            return retry_call(lambda: fn(*a, **kw), site,
                              policy=policy, classify=classify,
                              sleep=sleep)
        wrapper.__name__ = getattr(fn, "__name__", "retryable")
        wrapper.__doc__ = fn.__doc__
        return wrapper
    return deco
