"""Fault injection: a process-wide registry of named injection sites.

Real TPU fleets see flipped bits in checkpoints, transient I/O errors,
wedged collectives, and raising decode steps; none of those are
reproducible on demand, which is why recovery code rots.  This module
makes them reproducible: production code threads ``check(site)`` calls
through its failure-prone paths (the canonical sites are in
:data:`SITES`), and chaos tests arm a seeded-deterministic policy at a
site to make the real code path fail exactly there.

Cost discipline: disarmed (the default, and the only state production
ever runs in) a hook is ``if faults._armed: ...`` — one module-global
bool read; nothing else executes.  Hot paths (the serve decode loop,
the graph-step dispatch) guard the call with the flag themselves so
the disarmed cost is literally that one read.

Policies fire deterministically: :class:`FailRate` draws from its own
``random.Random(seed)``, :class:`FailOnce`/:class:`FailAfterN` count
calls — re-running a chaos test injects the identical fault sequence.
Every fired fault increments ``resilience.faults_injected{site=}`` in
the observe registry and emits a ``resilience/fault`` trace instant,
which is what lets CI assert "recovery count == injected count".
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager

from ..observe import trace as _trace
from ..observe.registry import registry as _registry
from ..utils.logging import get_channel

__all__ = ["SITES", "FaultInjected", "FailOnce", "FailRate",
           "FailAfterN", "Latency", "inject", "injected", "clear",
           "armed", "check"]

#: Canonical injection sites threaded through the codebase.  ``check``
#: accepts any name (subsystems may add their own), but these are the
#: ones production code hooks today.
SITES = (
    "checkpoint.write",    # model save path + Snapshot.write
    "checkpoint.read",     # model load path + Snapshot.read
    "comm.collective",     # host-side collective dispatch
    "serve.decode_step",   # the engine's pool decode (and prefill)
    "serve.ep_dispatch",   # expert-parallel sharded-twin dispatch
    "serve.pp_boundary",   # pipeline stage-boundary sharded dispatch
    "serve.prefill_chunk",  # budgeted chunked-prefill chunk dispatch
    "serve.prefix_copy",   # prefix-cache pool<->slot block copies
    "serve.route",         # fleet router admission (ServeFleet.submit)
    "serve.kv_ship",       # disaggregated KV ship (export + import)
    "serve.fork_copy",     # KV-fork copy-on-first-write block copy
    #                        (serve/paged.py copy_block — a fired
    #                        fault rejects ONLY the writing branch;
    #                        sibling branches keep decoding on their
    #                        intact shared bytes)
    "serve.autoscale",     # autoscaler scale-up/retire actions
    #                        (serve/autoscale.py — checked BEFORE any
    #                        replica construction or registration, so
    #                        a fired fault abandons the DECISION typed
    #                        and the fleet keeps serving)
    "serve.dist.rpc",      # dist-fleet control RPC to a worker peer
    #                        (serve/dist/fleet.py — a fired fault is a
    #                        PARTITION: the peer is marked gone and
    #                        the fleet fails over, exactly as if the
    #                        host dropped off the network)
    "serve.dist.frame",    # streamed KV ship frame relay to the
    #                        destination peer (a fired fault is a
    #                        HALF-SHIPPED image: staged frames are
    #                        aborted and the request replays cold)
    "io.binfile",          # BinFile record read/write
    "train.step",          # _GraphRunner step dispatch
)


class FaultInjected(RuntimeError):
    """Raised at an armed injection site.  ``transient`` feeds the
    retry layer's classification: transient injected faults are
    retried (modelling flaky I/O), fatal ones are not (modelling
    corruption)."""

    def __init__(self, site, message=None, transient=True):
        super().__init__(message or f"injected fault at {site}")
        self.site = site
        self.transient = transient


class _Policy:
    """Base policy: subclasses decide *whether* call N fires; the base
    owns *what* firing means (latency, then the optional error).
    ``latency_s`` alone (no error) models a slow but healthy path."""

    def __init__(self, transient=True, latency_s=0.0, error=None):
        self.transient = transient
        self.latency_s = float(latency_s)
        self.error = error  # optional exception INSTANCE to raise
        self.calls = 0
        self.fired = 0
        self._lock = threading.Lock()

    def _should_fire(self) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def fire(self, site):
        with self._lock:
            self.calls += 1
            hit = self._should_fire()
            if hit:
                self.fired += 1
        if self.latency_s > 0.0:
            time.sleep(self.latency_s)
        if not hit:
            return
        _registry().counter(
            "resilience.faults_injected",
            help="faults fired by the injection registry",
            site=site).inc()
        _trace.event("resilience/fault", cat="resilience", site=site,
                     policy=type(self).__name__, transient=self.transient)
        get_channel("resilience").warning(
            "injected fault at %s (%s, fired=%d)", site,
            type(self).__name__, self.fired)
        if self.error is not None:
            raise self.error
        raise FaultInjected(site, transient=self.transient)


class FailOnce(_Policy):
    """Fire on the first call, pass forever after — the canonical
    transient fault a retry should absorb."""

    def _should_fire(self):
        return self.fired == 0


class FailRate(_Policy):
    """Fire each call with probability ``rate``, drawn from a private
    seeded RNG — deterministic per (seed, call sequence)."""

    def __init__(self, rate, seed=0, **kw):
        super().__init__(**kw)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self._rng = random.Random(seed)

    def _should_fire(self):
        return self._rng.random() < self.rate


class FailAfterN(_Policy):
    """Pass the first ``n`` calls, then fire ``times`` consecutive
    calls (default 1), then pass again — "the run died at step N"."""

    def __init__(self, n, times=1, **kw):
        super().__init__(**kw)
        self.n = int(n)
        self.times = int(times)

    def _should_fire(self):
        return self.calls > self.n and self.fired < self.times


class Latency(_Policy):
    """Pure latency injection: every call sleeps ``latency_s`` and
    never raises — models a degraded-but-alive dependency."""

    def __init__(self, latency_s, **kw):
        super().__init__(latency_s=latency_s, **kw)

    def _should_fire(self):
        return False


# -- the registry -----------------------------------------------------------

_lock = threading.Lock()
_policies: dict = {}
# module-global arm flag: the ONLY thing a disarmed hook reads
_armed = False


def inject(site, policy) -> _Policy:
    """Arm ``policy`` at ``site`` (replacing any previous policy
    there).  Returns the policy so tests can read ``.fired``."""
    global _armed
    with _lock:
        _policies[site] = policy
        _armed = True
    return policy


def clear(site=None):
    """Disarm ``site``, or every site when None.  When the last policy
    goes, the module flag drops and every hook is a single bool read
    again."""
    global _armed
    with _lock:
        if site is None:
            _policies.clear()
        else:
            _policies.pop(site, None)
        _armed = bool(_policies)


@contextmanager
def injected(site, policy):
    """Scoped injection for tests: arm on entry, disarm on exit."""
    inject(site, policy)
    try:
        yield policy
    finally:
        clear(site)


def armed() -> bool:
    return _armed


def check(site):
    """The hook production code calls at an injection site.  Disarmed:
    one global read and return.  Armed with a policy at ``site``: the
    policy decides whether this call sleeps and/or raises."""
    if not _armed:
        return
    pol = _policies.get(site)
    if pol is not None:
        pol.fire(site)
