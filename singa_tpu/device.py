"""Devices and platform discovery for the TPU-native SINGA rebuild.

Reference parity (apache/singa, paths unverified — see SURVEY.md §2.1):
  - ``include/singa/core/device.h`` / ``src/core/device/device.cc``:
    ``Device`` base with ``Exec(fn, read_blocks, write_blocks)``, block
    allocation, ``CopyDataToFrom``.
  - ``src/core/device/cpp_cpu.cc`` (``CppCPU``),
    ``src/core/device/cuda_gpu.cc`` (``CudaGPU``: stream + cuBLAS/cuDNN
    handles + cnmem pool), ``src/core/device/platform.cc`` (``Platform``).
  - ``python/singa/device.py``: ``create_cuda_gpu(_on)``,
    ``get_default_device``.

TPU-native design: a singa ``Device`` wraps a ``jax.Device``. There is no
``Exec``/``Block``/stream machinery to rebuild — XLA owns HBM and the
dispatch queue, and SINGA's buffering graph scheduler
(``src/core/scheduler/scheduler.cc``) collapses into ``jax.jit`` tracing of
the whole train step (see ``model.py``).  What remains device state here:

  * placement: which ``jax.Device`` new tensors land on,
  * the graph flag (``EnableGraph`` — whether ``Model`` runs jitted),
  * a functional PRNG key (SINGA's per-device curand generator becomes a
    threaded ``jax.random`` key; graph mode treats it as traced state),
  * profiling verbosity (SINGA v3.1 per-op time profiling → ``jax.profiler``).
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np

__all__ = [
    "Device",
    "CppCPU",
    "TpuDevice",
    "create_tpu_device",
    "create_tpu_devices",
    "create_tpu_device_on",
    "create_cuda_gpu",
    "create_cuda_gpu_on",
    "create_cuda_gpus",
    "create_cuda_gpus_on",
    "get_default_device",
    "set_default_device",
    "enable_tensor_graph",
    "get_num_tpus",
    "device_query",
]

_lock = threading.Lock()


def _accelerator_devices():
    """Non-CPU jax devices THIS process can address (multi-host runtimes
    list every host's devices in jax.devices(); eager placement must
    stay on local chips), falling back to local CPU when none exist."""
    local = jax.local_devices()
    accel = [d for d in local if d.platform != "cpu"]
    return accel if accel else local


class Device:
    """Base device: placement + graph flag + PRNG + profiling verbosity.

    Mirrors ``singa::Device`` (include/singa/core/device.h, unverified) in
    API shape; the execution model is jax's async dispatch instead of
    ``Exec`` lambdas over ``Block`` dependencies.
    """

    def __init__(self, dev_id: int, jax_device, lang: str):
        self._id = int(dev_id)
        self.jax_device = jax_device
        self._lang = lang
        self.graph_enabled_ = False
        self.verbosity_ = 0
        self.skip_iteration_ = 5
        # Functional RNG: one key per device, split on demand.  In graph mode
        # Model treats this as part of the persistent traced state so random
        # ops (dropout, init) stay reproducible and jit-safe.
        seed = int.from_bytes(os.urandom(4), "little")
        self._rng_key = jax.random.PRNGKey(seed)

    # -- identity ----------------------------------------------------------
    def id(self) -> int:
        return self._id

    def lang(self) -> str:
        return self._lang

    @property
    def platform(self) -> str:
        return self.jax_device.platform

    def __repr__(self):
        return f"<{type(self).__name__} id={self._id} jax={self.jax_device}>"

    # -- RNG ---------------------------------------------------------------
    def SetRandSeed(self, seed: int):
        self._rng_key = jax.random.PRNGKey(int(seed))

    def rng_key(self):
        """Split and return a fresh subkey (mutates device key state)."""
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    # -- graph mode --------------------------------------------------------
    # SINGA: Device::EnableGraph buffers Exec lambdas into the scheduler
    # graph; here the flag tells Model.compile to jit the train step.
    def EnableGraph(self, enable: bool):
        self.graph_enabled_ = bool(enable)

    def graph_enabled(self) -> bool:
        return self.graph_enabled_

    def ResetGraph(self):
        """Drop compiled step caches (SINGA: Graph::Reset)."""
        from . import model as _model

        _model._clear_compiled_caches(self)

    # -- sync / profiling --------------------------------------------------
    def Sync(self):
        """Block until all queued work on this device is done."""
        (jax.device_put(0, self.jax_device) + 0).block_until_ready()

    def SetVerbosity(self, v: int):
        self.verbosity_ = int(v)

    def SetSkipIteration(self, n: int):
        self.skip_iteration_ = int(n)

    def PrintTimeProfiling(self):
        """Per-op profiling: the static XLA cost-analysis table of every
        compiled step, and — when a ``jax.profiler`` trace was captured
        via ``enable_profiling``/``disable_profiling`` — the MEASURED
        per-op/per-fusion durations parsed out of that trace, printed
        next to it.

        SINGA v3.1 prints CUDA-event timings per scheduler node; the
        cost table is the static analogue and the parsed trace is the
        measured one (true parity with the reference's v3.1 measured
        profiling — VERDICT weak #6).  Returns the measured-durations
        dict (``{op name: {"count", "total_us"}}``; empty when no trace
        was captured) so tests and tooling can assert on it.
        """
        from . import model as _model

        for fn, cost in _model._compiled_cost_tables(self):
            print(f"== time profiling for compiled step {fn} ==")
            # raw jax cost_analysis() is a one-element LIST of dicts
            # on some versions — normalize exactly like _cost_args
            # (latent crash whenever any compiled step existed)
            c = (cost[0] if isinstance(cost, (list, tuple)) and cost
                 else cost)
            if isinstance(c, dict):
                for k, v in sorted(c.items()):
                    print(f"  {k}: {v}")
        measured = self.profiled_durations()
        if measured:
            print("== measured durations (jax.profiler trace, "
                  f"{len(measured)} distinct ops) ==")
            top = sorted(measured.items(),
                         key=lambda kv: -kv[1]["total_us"])[:32]
            for name, rec in top:
                print(f"  {name}: {rec['total_us']:.1f} us over "
                      f"{rec['count']} event(s)")
        return measured

    def profiled_durations(self) -> dict:
        """Measured per-op durations from the last profiler capture:
        parse the newest trace-event JSON under the ``enable_profiling``
        logdir and aggregate every complete ("ph" == "X") event's
        duration by op name — XLA thunk/fusion events ("dot.3",
        "multiply_multiply_fusion", executable dispatch) survive, host
        Python frame events (``$file.py:line`` names) are dropped.
        ``{}`` when no capture exists; never raises (profiling is a
        diagnostic, not a dependency)."""
        logdir = getattr(self, "_profile_dir", None)
        if not logdir:
            return {}
        import glob
        import gzip
        import json

        try:
            paths = sorted(
                glob.glob(os.path.join(logdir, "**",
                                       "*.trace.json.gz"),
                          recursive=True),
                key=os.path.getmtime)
            if not paths:
                return {}
            with gzip.open(paths[-1], "rt") as fh:
                trace = json.load(fh)
        except Exception:
            return {}
        out = {}
        for e in trace.get("traceEvents", []):
            if e.get("ph") != "X" or not e.get("dur"):
                continue
            name = e.get("name", "")
            # host-side Python frame annotations ("$profiler.py:91
            # start_trace", "file.py:123 fn") are tracing overhead,
            # not device work
            if name.startswith("$") or ".py:" in name:
                continue
            rec = out.setdefault(name, {"count": 0, "total_us": 0.0})
            rec["count"] += 1
            rec["total_us"] += float(e["dur"])
        return out

    def enable_profiling(self, logdir: str = "/tmp/singa_tpu_trace"):
        jax.profiler.start_trace(logdir)
        self._profile_dir = logdir

    def disable_profiling(self):
        jax.profiler.stop_trace()


class CppCPU(Device):
    """Host CPU device (reference: src/core/device/cpp_cpu.cc, unverified)."""

    def __init__(self, dev_id: int = -1):
        cpus = ([d for d in jax.local_devices(backend="cpu")]
                if _has_cpu_backend() else jax.local_devices())
        idx = 0 if dev_id < 0 else dev_id % len(cpus)
        super().__init__(dev_id, cpus[idx], "kCpp")


class TpuDevice(Device):
    """Accelerator device — the rebuild of ``CudaGPU``
    (src/core/device/cuda_gpu.cc, unverified).  No stream/handle/cnmem
    state survives the port: XLA's client owns HBM and execution order.
    """

    def __init__(self, dev_id: int = 0, jax_device=None):
        if jax_device is None:
            accel = _accelerator_devices()
            jax_device = accel[dev_id % len(accel)]
        super().__init__(dev_id, jax_device, "kTpu")


def _has_cpu_backend() -> bool:
    try:
        jax.devices("cpu")
        return True
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# Platform (reference: src/core/device/platform.cc, unverified)
# ---------------------------------------------------------------------------

_default_device: Device | None = None
_device_cache: dict = {}


def _cached(kind, dev_id, ctor):
    with _lock:
        key = (kind, dev_id)
        if key not in _device_cache:
            _device_cache[key] = ctor()
        return _device_cache[key]


def get_num_tpus() -> int:
    return len(_accelerator_devices())


def create_tpu_device(dev_id: int = 0) -> TpuDevice:
    return _cached("tpu", dev_id, lambda: TpuDevice(dev_id))


def create_tpu_device_on(dev_id: int) -> TpuDevice:
    return create_tpu_device(dev_id)


def create_tpu_devices(num: int) -> list:
    return [create_tpu_device(i) for i in range(num)]


# SINGA-compatible creators (python/singa/device.py, unverified).  Per the
# north star, reference train scripts switch to TPU by changing only the
# device-creation line; aliasing the CUDA creators to the accelerator device
# means even that change is optional.
def create_cuda_gpu(set_default: bool = True):
    return create_tpu_device(0)


def create_cuda_gpu_on(dev_id: int, set_default: bool = True):
    return create_tpu_device(dev_id)


def create_cuda_gpus(num: int):
    return create_tpu_devices(num)


def create_cuda_gpus_on(dev_ids):
    return [create_tpu_device(i) for i in dev_ids]


def get_default_device() -> Device:
    global _default_device
    with _lock:
        if _default_device is None:
            _default_device = CppCPU(-1)
        return _default_device


def set_default_device(dev: Device):
    global _default_device
    _default_device = dev


def enable_tensor_graph(enable: bool = True):
    """Convenience: toggle graph mode on the default device."""
    get_default_device().EnableGraph(enable)


# ---------------------------------------------------------------------------
# Memory-pool API shims (reference: src/core/memory/memory.cc — CnMemPool /
# CudaMemPool device allocators, SURVEY.md §2.1 Memory-pool row: "no-op
# shim (XLA owns HBM); keep API for source compat").  Scripts that
# construct a pool and pass it to device creation keep working; the pool
# only tracks what it was asked for, since allocation itself belongs to
# the XLA client.
# ---------------------------------------------------------------------------


class DeviceMemPool:
    """API-compat allocator shim; XLA's client owns real HBM."""

    def __init__(self, init_size_mb: int = 256, max_size_mb: int = 0):
        self.init_size_mb = int(init_size_mb)
        self.max_size_mb = int(max_size_mb)
        self._outstanding = 0  # bytes "allocated" through the shim API

    def Malloc(self, size: int) -> int:
        self._outstanding += int(size)
        return 0  # opaque handle; nothing real to hand out

    def Free(self, ptr: int, size: int = 0) -> None:
        self._outstanding = max(0, self._outstanding - int(size))

    def GetMemUsage(self):
        """(free, total) in bytes, from the live backend when it reports
        memory stats, else (0, 0) like a CPU pool."""
        try:
            stats = jax.devices()[0].memory_stats() or {}
            total = stats.get("bytes_limit", 0)
            used = stats.get("bytes_in_use", 0)
            return (total - used, total)
        except Exception:
            return (0, 0)


class CnMemPool(DeviceMemPool):
    """Reference cnmem-backed pool name, kept for source compat."""


class CudaMemPool(DeviceMemPool):
    """Reference CUDA pool name, kept for source compat."""


def device_query(dev_id: int = 0, verbose: bool = False):
    devs = jax.devices()
    info = {
        "num_devices": len(devs),
        "platforms": sorted({d.platform for d in devs}),
        "devices": [str(d) for d in devs] if verbose else None,
    }
    return info
