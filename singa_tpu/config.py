"""Build/capability flags.

Reference parity: apache/singa surfaces compile-time CMake options
(``USE_CUDA``, ``USE_DNNL``, ``ENABLE_DIST``, ... baked into
``singa_config.h.in`` — see SURVEY.md §5.6, unverified paths) to Python.
Here the stack is a single-language JAX/XLA build, so the flags are computed
at import time from the live environment instead of at compile time.
"""

import jax

# The TPU-native stack replaces SINGA's CUDA/cuDNN/OpenCL backends entirely.
USE_CUDA = False
USE_CUDNN = False
USE_OPENCL = False
USE_DNNL = False

# JAX is always present; an accelerator backend may or may not be.
USE_TPU = any(d.platform in ("tpu", "axon") for d in jax.devices())
USE_PYTHON = True

# Distributed training (DistOpt over ICI/DCN collectives) is always compiled
# in: jax collectives need no extra build flag, unlike NCCL/MPI.
ENABLE_DIST = True

CPP_VERSION = None  # no native C++ tensor core; see native/ for IO helpers
VERSION = "0.1.0"
