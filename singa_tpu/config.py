"""Build/capability flags.

Reference parity: apache/singa surfaces compile-time CMake options
(``USE_CUDA``, ``USE_DNNL``, ``ENABLE_DIST``, ... baked into
``singa_config.h.in`` — see SURVEY.md §5.6, unverified paths) to Python.
Here the stack is a single-language JAX/XLA build, so the flags are computed
at import time from the live environment instead of at compile time.
"""

import jax

# The TPU-native stack replaces SINGA's CUDA/cuDNN/OpenCL backends entirely.
USE_CUDA = False
USE_CUDNN = False
USE_OPENCL = False
USE_DNNL = False

# JAX is always present; an accelerator backend may or may not be.
# USE_TPU is resolved lazily (module __getattr__ below): calling
# jax.devices() at import time would initialize the XLA backend as a side
# effect of `import singa_tpu`, which breaks jax.distributed.initialize
# (it must run before any backend init) for multi-host users.
USE_PYTHON = True


def _use_tpu() -> bool:
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def __getattr__(name):
    if name == "USE_TPU":
        return _use_tpu()
    raise AttributeError(name)

# Distributed training (DistOpt over ICI/DCN collectives) is always compiled
# in: jax collectives need no extra build flag, unlike NCCL/MPI.
ENABLE_DIST = True

CPP_VERSION = None  # no native C++ tensor core; see native/ for IO helpers
VERSION = "0.2.0"

# ---------------------------------------------------------------------------
# Debug mode (SURVEY.md §5.2): the reference has no sanitizers — scheduler
# read/write edges are its only race protection.  The TPU analogue: jit
# purity makes races structurally impossible, and JAX already raises on
# any host access to a donated buffer; debug mode adds the check that
# still matters on this stack — NaN detection inside compiled steps
# (jax_debug_nans re-runs the offending op eagerly and raises at the op,
# not three steps later).
# ---------------------------------------------------------------------------

_debug = False


def debug(enable: bool = True) -> None:
    """Toggle NaN-checking debug mode (jax_debug_nans).  Costs a re-run
    per detected NaN only; keep off for benchmarking."""
    global _debug
    _debug = bool(enable)
    jax.config.update("jax_debug_nans", _debug)


def debug_enabled() -> bool:
    return _debug
