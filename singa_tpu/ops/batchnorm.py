"""Batch normalization (reference: src/model/operation/batchnorm.{h,cc},
unverified — cuDNN spatial BN fwd/bwd with saved mean/inv-var and running
stats).

TPU-native, HBM-roofline-aware (the round-3 ResNet profile showed BN
dominating the non-conv 32% of the step): all activation-sized math
stays in the compute dtype (bf16 under amp), while per-channel
STATISTICS accumulate in fp32 via reduction dtypes — no fp32
materialization of the (N,C,H,W) activation, and a custom VJP whose
residuals are the bf16 input plus tiny per-channel vectors (jax.vjp of
the naive fp32 formulation pinned fp32 copies of every activation).
Mean is removed before squaring (two-pass variance), so large-mean
inputs keep fp32-accurate statistics — the property
tests/test_amp.py::test_norm_stats_fp32_under_amp asserts.

Running stats live on the BatchNorm2d layer as state Tensors; the op
returns (y, batch_mean, batch_var) and the layer rebinds running stats
from the stop_gradient'd batch stats, which graph mode threads through
the compiled step like any other persistent state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import autograd
from ..autograd import _op


def _channel_f32(a):
    """(C,) fp32 vector -> broadcastable NCHW shape."""
    return a[None, :, None, None]


def _stats(x):
    """Per-channel (mean, var) in fp32 over (N, H, W) without
    materializing an fp32 activation: reductions accumulate in fp32,
    elementwise centering stays in x.dtype."""
    m = jnp.mean(x, (0, 2, 3), dtype=jnp.float32)
    xc = x - _channel_f32(m).astype(x.dtype)
    v = jnp.mean(jnp.square(xc), (0, 2, 3), dtype=jnp.float32)
    return m, v, xc


@jax.custom_vjp
def _bn_train(x, scale, bias, eps):
    m, v, xc = _stats(x)
    a = _channel_f32(scale * jax.lax.rsqrt(v + eps)).astype(x.dtype)
    y = xc * a + _channel_f32(bias).astype(x.dtype)
    return y, m, v


def _bn_train_fwd(x, scale, bias, eps):
    y, m, v = _bn_train(x, scale, bias, eps)
    inv = jax.lax.rsqrt(v + eps)
    return (y, m, v), (x, m, inv, scale, eps)


def _bn_train_bwd(res, cts):
    """Spatial-BN backward, activation math in x.dtype, per-channel
    sums in fp32:
      dx = scale*inv*(dy - Σdy/n - xc*inv²*Σ(dy·xc)/n)
           [+ dm_ct/n + 2·xc·dv_ct/n for the stat outputs]
      dscale = inv·Σ(dy·xc),  dbias = Σdy
    """
    x, m, inv, scale, eps = res
    dy, dm_ct, dv_ct = cts
    n = x.shape[0] * x.shape[2] * x.shape[3]
    xc = x - _channel_f32(m).astype(x.dtype)
    sum_dy = jnp.sum(dy, (0, 2, 3), dtype=jnp.float32)
    sum_dy_xc = jnp.sum(dy * xc, (0, 2, 3), dtype=jnp.float32)

    # dx = c1*dy + c3*xc + c2 with per-channel f32 coefficients; the
    # dm_ct/dv_ct terms are the direct cotangents of the (m, v) outputs
    # (zero when stats feed only stop_gradient'd running updates)
    c1 = scale * inv
    c2 = -c1 * (sum_dy / n) + dm_ct / n
    c3 = -scale * (inv ** 3) * (sum_dy_xc / n) + 2.0 * dv_ct / n
    dx = (dy * _channel_f32(c1).astype(x.dtype)
          + xc * _channel_f32(c3).astype(x.dtype)
          + _channel_f32(c2).astype(x.dtype))
    dscale = (inv * sum_dy_xc).astype(scale.dtype)
    dbias = sum_dy.astype(scale.dtype)
    return dx, dscale, dbias, None


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def batchnorm2d(x, scale, bias, running_mean, running_var,
                momentum=0.9, eps=1e-5):
    """NCHW spatial BN.  Training: normalize by batch stats (computed
    ONCE, shared with the running-stat update) and update running stats
    (running = momentum*running + (1-momentum)*batch, the reference's
    convention).  Eval: normalize by running stats."""
    if autograd.training:
        def f(xv, sv, bv_, eps=eps):
            return _bn_train(xv, sv, bv_, eps)

        y, bm, bv = _op(f, x, scale, bias, _name="BatchNorm2d")
        # running-stat refs ride the op instance so sonnx can export a
        # proper 5-input BatchNormalization node (sonnx._dec_batchnorm)
        y.creator.params = {"eps": eps, "momentum": momentum,
                            "rm": running_mean, "rv": running_var}
        if autograd.exporting:
            # export taping must be pure: skip the stat update so the
            # exported initializers hold the pre-forward running stats
            return y
        running_mean.data = (
            momentum * running_mean.data
            + (1.0 - momentum) * jax.lax.stop_gradient(bm.data))
        running_var.data = (
            momentum * running_var.data
            + (1.0 - momentum) * jax.lax.stop_gradient(bv.data))
        return y

    rm = running_mean.data
    rv = running_var.data

    def f(xv, sv, bv_, rm=rm, rv=rv, eps=eps):
        a = _channel_f32(sv * jax.lax.rsqrt(rv + eps)).astype(xv.dtype)
        b = _channel_f32(bv_ - sv * jax.lax.rsqrt(rv + eps) * rm)
        return xv * a + b.astype(xv.dtype)

    # (no export metadata here: tape edges only exist when
    # autograd.training is True, which always takes the branch above —
    # an eval-mode BN op can never appear on an export tape)
    return _op(f, x, scale, bias, _name="BatchNorm2dEval")
