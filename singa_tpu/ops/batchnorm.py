"""Batch normalization (reference: src/model/operation/batchnorm.{h,cc},
unverified — cuDNN spatial BN fwd/bwd with saved mean/inv-var and running
stats).

TPU-native: the normalization is one pure jnp function whose VJP (via
jax.vjp) covers the full dependence on batch statistics — no hand-written
cuDNN-mirror backward.  Running stats live on the BatchNorm2d layer as
state Tensors; their update is a functional rebind with stop_gradient'd
batch stats, which graph mode threads through the compiled step like any
other persistent state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import autograd
from ..autograd import _op


def batchnorm2d(x, scale, bias, running_mean, running_var,
                momentum=0.9, eps=1e-5):
    """NCHW spatial BN.  Training: normalize by batch stats and update
    running stats (running = momentum*running + (1-momentum)*batch, the
    reference's convention).  Eval: normalize by running stats."""
    if autograd.training:
        axes = (0, 2, 3)
        xf32 = x.data.astype(jnp.float32)  # stats in fp32 under amp
        bm = jnp.mean(xf32, axes)
        bv = jnp.var(xf32, axes)
        running_mean.data = (momentum * running_mean.data
                             + (1.0 - momentum) * jax.lax.stop_gradient(bm))
        running_var.data = (momentum * running_var.data
                            + (1.0 - momentum) * jax.lax.stop_gradient(bv))

        def f(xv, sv, bv_, eps=eps):
            xf = xv.astype(jnp.float32)
            m = jnp.mean(xf, (0, 2, 3), keepdims=True)
            v = jnp.var(xf, (0, 2, 3), keepdims=True)
            inv = jax.lax.rsqrt(v + eps)
            y = (xf - m) * inv * sv[None, :, None, None] \
                + bv_[None, :, None, None]
            return y.astype(xv.dtype)

        return _op(f, x, scale, bias, _name="BatchNorm2d")

    rm = running_mean.data
    rv = running_var.data

    def f(xv, sv, bv_, rm=rm, rv=rv, eps=eps):
        xf = xv.astype(jnp.float32)
        inv = jax.lax.rsqrt(rv + eps)[None, :, None, None]
        y = (xf - rm[None, :, None, None]) * inv * sv[None, :, None, None] \
            + bv_[None, :, None, None]
        return y.astype(xv.dtype)

    return _op(f, x, scale, bias, _name="BatchNorm2dEval")
