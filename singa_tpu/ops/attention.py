"""Attention (the reference has no native attention — BERT's arrives via
ONNX-imported GEMM+softmax graphs, SURVEY.md §5.7; this module is the
TPU-native first-class version).

Default path: one fused jnp scaled-dot-product (XLA fuses the softmax
chain into the matmuls on the MXU).  ``use_flash=True`` routes through
the Pallas flash-attention kernel (ops/pallas/flash_attention.py) for
long sequences where the S×S score matrix shouldn't materialize in HBM.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import autograd
from ..autograd import _op
from ..layer import Layer, Linear
from ..tensor import Tensor


def scaled_dot_product_attention(q, k, v, mask=None, use_flash=False,
                                 remat=False):
    """q,k,v: Tensors (B, H, S, D); mask: optional additive mask
    broadcastable to (B, H, S, S) (e.g. -1e9 at padded positions).
    ``remat=True`` recomputes the S x S score/prob tensors in backward
    (jax.checkpoint) instead of keeping them resident."""
    if use_flash:
        from .pallas.flash_attention import flash_attention_op

        return flash_attention_op(q, k, v, mask)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def f(qv, kv, vv, *rest, scale):
        scores = jnp.einsum("bhsd,bhtd->bhst", qv, kv) * scale
        if rest:
            scores = scores + rest[0]
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, vv)

    # scale rides op.params so the sonnx frontend can decompose the
    # fused op into MatMul/Mul/Softmax nodes (sonnx._decompose_attention)
    apply = autograd.checkpoint_op if remat else _op
    if mask is None:
        return apply(f, q, k, v, _name="Attention", scale=scale)
    return apply(f, q, k, v, mask, _name="Attention", scale=scale)


class MultiHeadAttention(Layer):
    """Standard MHA over (B, S, E) inputs.  ``num_kv_heads`` <
    ``num_heads`` gives grouped-query attention: k/v project to
    ``num_kv_heads`` heads, each broadcast over its query group before
    the score contraction (RepeatKV — see the parallel variant,
    parallel/tensor_parallel.py ParallelMHA, for the sharded story)."""

    def __init__(self, num_heads, dropout=0.0, use_flash=False,
                 remat=False, num_kv_heads=None):
        super().__init__()
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads or num_heads)
        if self.num_heads % self.num_kv_heads != 0:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {self.num_kv_heads}")
        self.dropout = float(dropout)
        self.use_flash = use_flash
        self.remat = bool(remat)
        self.q_proj = Linear(0)  # out_features fixed at initialize
        self.k_proj = Linear(0)
        self.v_proj = Linear(0)
        self.out_proj = Linear(0)

    def initialize(self, x, mask=None):
        e = x.shape[-1]
        assert e % self.num_heads == 0
        e_kv = (e // self.num_heads) * self.num_kv_heads
        for proj in (self.q_proj, self.out_proj):
            proj.out_features = e
        for proj in (self.k_proj, self.v_proj):
            proj.out_features = e_kv

    def forward(self, x, mask=None):
        b, s, e = x.shape
        h = self.num_heads
        h_kv = self.num_kv_heads
        d = e // h

        def split_heads(t, nh):
            t = autograd.reshape(t, (b, s, nh, d))
            t = autograd.transpose(t, (0, 2, 1, 3))
            if nh != h:
                t = autograd.repeat_kv(t, h // nh)
            return t

        q = split_heads(self.q_proj(x), h)
        k = split_heads(self.k_proj(x), h_kv)
        v = split_heads(self.v_proj(x), h_kv)
        ctx = scaled_dot_product_attention(q, k, v, mask,
                                           use_flash=self.use_flash,
                                           remat=self.remat)
        ctx = autograd.transpose(ctx, (0, 2, 1, 3))
        ctx = autograd.reshape(ctx, (b, s, e))
        if self.dropout > 0:
            ctx = autograd.dropout(ctx, self.dropout)
        return self.out_proj(ctx)
