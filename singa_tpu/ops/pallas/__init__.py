"""Pallas TPU custom kernels (flash attention, fused LSTM cell)."""
