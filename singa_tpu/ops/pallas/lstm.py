"""Fused LSTM recurrence — Pallas TPU kernel (north star: "LSTM char-RNN
language model (cudnn_rnn → Pallas scan)").

Design (the cuDNN trick, TPU-flavored):
  * the input projection for ALL timesteps is one big MXU GEMM done
    outside the kernel:  gx = x @ W_ih^T + b   with shape (T, B, 4H);
  * the sequential part — h @ W_hh^T plus the gate nonlinearities —
    runs inside ONE Pallas kernel that keeps h, c and W_hh resident in
    VMEM across all T steps, so the recurrence never round-trips HBM
    (the lax.scan version reloads W_hh's tile stream every step).

Backward is the VJP of the lax.scan reference (identical math), so the
kernel is a drop-in for training.  Gated: single layer, unidirectional,
and (T·B·4H + 4H·H) floats must fit VMEM; ops/rnn.py falls back to the
scan path otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# conservative VMEM budget for inputs residing in the kernel (bytes)
VMEM_BUDGET = 10 * 1024 * 1024


def fits_vmem(t, b, h, dtype_bytes=4):
    need = (t * b * 4 * h      # gx
            + 4 * h * h        # W_hh
            + t * b * h        # y out
            + 2 * b * h) * dtype_bytes
    return need < VMEM_BUDGET


def _lstm_kernel(gx_ref, whh_ref, h0_ref, c0_ref, y_ref, hN_ref, cN_ref):
    """gx: (T, B, 4H); whh: (4H, H); h0/c0: (B, H); y: (T, B, H)."""
    T = gx_ref.shape[0]
    H = h0_ref.shape[1]

    def step(t, carry):
        h, c = carry
        g = gx_ref[t] + jnp.dot(h, whh_ref[:].T,
                                preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(g[:, 0 * H:1 * H])
        f = jax.nn.sigmoid(g[:, 1 * H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:4 * H])
        c = f * c + i * gg
        h = o * jnp.tanh(c)
        y_ref[t] = h.astype(y_ref.dtype)
        return (h, c)

    h, c = jax.lax.fori_loop(0, T, step, (h0_ref[:], c0_ref[:]))
    hN_ref[:] = h.astype(hN_ref.dtype)
    cN_ref[:] = c.astype(cN_ref.dtype)


def _pallas_recurrence(gx, w_hh, h0, c0):
    T, B, G = gx.shape
    H = h0.shape[1]
    interpret = jax.default_backend() == "cpu"
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _lstm_kernel,
        in_specs=[vmem, vmem, vmem, vmem],
        out_specs=(vmem, vmem, vmem),
        out_shape=(jax.ShapeDtypeStruct((T, B, H), gx.dtype),
                   jax.ShapeDtypeStruct((B, H), gx.dtype),
                   jax.ShapeDtypeStruct((B, H), gx.dtype)),
        interpret=interpret,
    )(gx, w_hh, h0, c0)


def _scan_reference(gx, w_hh, h0, c0):
    H = h0.shape[1]

    def step(carry, g_t):
        h, c = carry
        g = g_t + h @ w_hh.T
        i = jax.nn.sigmoid(g[:, 0 * H:1 * H])
        f = jax.nn.sigmoid(g[:, 1 * H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:4 * H])
        c = f * c + i * gg
        h = o * jnp.tanh(c)
        return (h, c), h

    (h, c), ys = jax.lax.scan(step, (h0, c0), gx)
    return ys, h, c


@jax.custom_vjp
def _lstm_recurrence(gx, w_hh, h0, c0):
    return _pallas_recurrence(gx, w_hh, h0, c0)


def _fwd(gx, w_hh, h0, c0):
    out = _pallas_recurrence(gx, w_hh, h0, c0)
    return out, (gx, w_hh, h0, c0)


def _bwd(res, cts):
    gx, w_hh, h0, c0 = res
    _, vjp = jax.vjp(_scan_reference, gx, w_hh, h0, c0)
    return vjp(cts)


_lstm_recurrence.defvjp(_fwd, _bwd)


def pallas_lstm(x, w_ih, w_hh, b, h0, c0, use_pallas=True):
    """Full LSTM layer over time: x (T, B, I) -> (y (T, B, H), hN, cN).

    w_ih: (4H, I), w_hh: (4H, H), b: (4H,) — the packed-handle slices
    from ops/rnn.py (i,f,g,o gate order)."""
    T, B, _ = x.shape
    H = w_hh.shape[1]
    # the parallel part: one big MXU GEMM over all timesteps
    gx = jnp.einsum("tbi,gi->tbg", x, w_ih) + b
    if use_pallas and fits_vmem(T, B, H):
        return _lstm_recurrence(gx, w_hh, h0, c0)
    return _scan_reference(gx, w_hh, h0, c0)
