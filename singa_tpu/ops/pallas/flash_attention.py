"""Flash attention — Pallas TPU kernel.

The reference has no native attention (BERT arrives via ONNX GEMM+softmax
graphs that materialize the S×S score matrix — SURVEY.md §5.7).  This
kernel is the TPU-native upgrade: online-softmax tiling keeps the score
matrix in VMEM block by block, so HBM traffic stays O(S·D) instead of
O(S²) — the enabler for long-context work (see parallel/ring_attention.py
for the multi-chip sequence-parallel version).

Forward: Pallas kernel, grid over (batch*heads, query blocks); each step
streams key/value blocks through VMEM with a running (max, denom, acc)
online softmax.  Backward: blockwise via jax.vjp of the lax.scan
reference — which XLA reverses by SAVING per-step residuals, i.e. the
backward is O(S²) memory, not O(S·D).

**Measured status (LONGCTX.json, v5e, round 3): demoted from the
training path.**  The XLA fused path beats this kernel on throughput at
every S in {512..4096} (kernel ~5% MFU under xprof) and, because of the
scan-reversal residuals, on training memory too; the production
long-context lever is ``remat=True`` on the fused path (only
fused+remat survives S=8192 on one chip).  The kernel's O(S·D) FORWARD
remains useful for inference and as the Pallas exemplar; a competitive
training story needs true flash backward kernels (dq/dk/dv with block
recomputation in-kernel).

Supports an optional additive key mask of shape (BH, S) (e.g. BERT's
padding mask) and a causal flag.  D (head dim) must be <= 128 and S a
multiple of the block size; ops/attention.py falls back to the fused-jnp
path otherwise.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, block_k, scale,
               causal, block_q):
    """One (batch*head, q-block) grid step.

    q_ref: (block_q, D); k_ref/v_ref: (S, D); mask_ref: (1, S) additive;
    o_ref: (block_q, D).
    """
    q = q_ref[:] * scale
    s_total = k_ref.shape[0]
    num_kb = s_total // block_k
    d = q_ref.shape[1]

    qi = pl.program_id(1)

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        s = s + mask_ref[0, pl.ds(kb * block_k, block_k)][None, :]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, mask, causal, block_q, block_k):
    """q,k,v: (BH, S, D); mask: (BH, S) additive (reshaped to (BH,1,S)
    for the kernel's tiling constraints)."""
    mask = mask[:, None, :]
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block_q)
    kernel = functools.partial(_fa_kernel, block_k=block_k, scale=scale,
                               causal=causal, block_q=block_q)
    interpret = jax.default_backend() == "cpu"  # no Mosaic on CPU (tests)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, mask)


def _blockwise_reference(q, k, v, mask, causal, block_k):
    """Numerically identical online-softmax attention built from a
    lax.scan over key blocks — used for the backward pass (its VJP never
    materializes S×S) and as the non-Pallas fallback."""
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qs = q * scale
    num_kb = s // block_k
    k_blocks = k.reshape(bh, num_kb, block_k, d).transpose(1, 0, 2, 3)
    v_blocks = v.reshape(bh, num_kb, block_k, d).transpose(1, 0, 2, 3)
    m_blocks = mask.reshape(bh, num_kb, block_k).transpose(1, 0, 2)

    q_pos = jnp.arange(s)[None, :, None]  # (1, S, 1)

    def body(carry, inp):
        acc, m_prev, l_prev = carry
        kb_idx, kb, vb, mb = inp
        sc = jnp.einsum("bqd,bkd->bqk", qs, kb) + mb[:, None, :]
        if causal:
            k_pos = kb_idx * block_k + jnp.arange(block_k)[None, None, :]
            sc = jnp.where(q_pos >= k_pos, sc, NEG_INF)
        m_cur = jnp.max(sc, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(sc - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bqk,bkd->bqd", p, vb)
        return (acc, m_new, l_new), None

    init = (jnp.zeros((bh, s, d), jnp.float32),
            jnp.full((bh, s), NEG_INF, jnp.float32),
            jnp.zeros((bh, s), jnp.float32))
    (acc, m, l), _ = jax.lax.scan(
        body, init, (jnp.arange(num_kb), k_blocks, v_blocks, m_blocks))
    return (acc / l[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, mask, causal, block_q, block_k):
    return _flash_fwd_pallas(q, k, v, mask, causal, block_q, block_k)


def _flash_fwd(q, k, v, mask, causal, block_q, block_k):
    o = _flash_fwd_pallas(q, k, v, mask, causal, block_q, block_k)
    return o, (q, k, v, mask)


def _flash_bwd(causal, block_q, block_k, res, do):
    q, k, v, mask = res
    # memory-efficient gradient: differentiate the blockwise-scan
    # reference (same math as the kernel) — XLA reverses the scan, so
    # peak memory stays O(S·D) per block
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_reference(q_, k_, v_, mask, causal,
                                                block_k), q, k, v)
    dq, dk, dv = vjp(do)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    force_reference=False):
    """q,k,v: (B, H, S, D) raw jax arrays; mask: additive, broadcastable
    to (B, H, S, S) but only key-mask shapes (B, 1, 1, S) are accepted by
    the kernel path.  Returns (B, H, S, D)."""
    b, h, s, d = q.shape
    # the Mosaic kernel keeps the STRICT original-block divisibility
    # guard (arbitrary clamped blocks would violate TPU tile alignment);
    # unaligned/short S falls back to the blockwise reference, whose
    # block only needs to divide S — shrink it to S when it doesn't
    kernel_ok = s % block_q == 0 and s % block_k == 0
    if s % block_k != 0 or block_k > s:
        block_k = s
    if block_q > s:
        block_q = s
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    if mask is None:
        mf = jnp.zeros((bh, s), q.dtype)
    else:
        if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
            mf = jnp.broadcast_to(mask[:, 0, 0, :], (b, s))
            mf = jnp.repeat(mf, h, axis=0)
        else:
            force_reference = True
            mf = None
    use_kernel = not force_reference and d <= 128 and kernel_ok
    if not use_kernel:
        if mf is None:
            # general mask: fall back to fused jnp with full mask
            scale = 1.0 / math.sqrt(d)
            sc = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale + mask
            p = jax.nn.softmax(sc, axis=-1)
            return jnp.einsum("bhst,bhtd->bhsd", p, v)
        o = _blockwise_reference(qf, kf, vf, mf, causal, block_k)
        return o.reshape(b, h, s, d)
    o = _flash(qf, kf, vf, mf, causal, block_q, block_k)
    return o.reshape(b, h, s, d)


def flash_attention_op(q, k, v, mask=None, causal=False, remat=False):
    """Tensor-level autograd op (used by ops/attention.py and the
    tensor_parallel flash path).

    Recorded as ``TPAttention`` with the same ``scale``/``causal``
    params as the fused path: the kernel computes the identical math
    (scale = 1/sqrt(D) internally), so sonnx's decomposed attention
    export covers flash-built models too.  ``remat`` wraps the op in
    jax.checkpoint for API symmetry with the fused path (measured
    neutral here — the flash backward's scan-reversal residuals, not
    the forward's, dominate; see LONGCTX.json)."""
    from ...autograd import _op, checkpoint_op  # local import, no cycles

    apply = checkpoint_op if remat else _op
    scale = 1.0 / math.sqrt(q.shape[-1])
    if mask is None:
        return apply(
            lambda qv, kv, vv, scale, causal: flash_attention(
                qv, kv, vv, causal=causal),
            q, k, v, _name="TPAttention", scale=scale, causal=causal)
    return apply(
        lambda qv, kv, vv, mv, scale, causal: flash_attention(
            qv, kv, vv, mv, causal=causal),
        q, k, v, mask, _name="TPAttention", scale=scale, causal=causal)
