"""Flash attention — Pallas TPU kernels, forward AND backward.

The reference has no native attention (BERT arrives via ONNX GEMM+softmax
graphs that materialize the S×S score matrix — SURVEY.md §5.7).  This
kernel is the TPU-native upgrade: online-softmax tiling keeps the score
matrix in VMEM block by block, so HBM traffic stays O(S·D) instead of
O(S²) — the enabler for long-context training (see
parallel/ring_attention.py for the multi-chip sequence-parallel version).

Design (canonical TPU flash schedule):

* **Forward** — grid ``(B·H, S/block_q, S/block_k)``; the innermost
  key-block dimension iterates sequentially on the core, carrying the
  online-softmax state ``(acc, m, l)`` in VMEM scratch that is zeroed at
  ``j == 0`` and flushed to the output block at ``j == n_k - 1``.  The
  kernel emits the per-row logsumexp ``L = m + log(l)`` as a second
  output — the only residual (beyond q/k/v/o) the backward needs.
* **Backward** — two kernels that RECOMPUTE attention probabilities
  blockwise from the saved logsumexp (``p = exp(s - L)``), never
  materializing S×S in HBM:
  - ``dq``: grid ``(B·H, n_q, n_k)``, accumulates ``Σ_j ds·K_j`` in a
    VMEM scratch across the sequential k dimension;
  - ``dk/dv``: grid ``(B·H, n_k, n_q)`` (q innermost), accumulates
    ``Σ_i dsᵀ·Q_i`` and ``Σ_i pᵀ·dO_i``.
  The softmax-Jacobian contraction uses the standard
  ``ds = p ∘ (dp − δ)`` identity with ``δ = rowsum(dO ∘ O)`` computed
  once outside the kernels.
* **Causal** — fully-above-diagonal blocks are skipped with ``pl.when``
  (≈2× compute saved at long S); diagonal blocks mask with iota.

This replaces the round-2 design whose backward differentiated a
``lax.scan`` reference — XLA's scan reversal saved per-step residuals,
i.e. O(S²) backward memory, which is why LONGCTX.json (round 3, first
half) recorded the kernel losing to the fused path everywhere.  The
rewritten kernels' training memory is O(S·D) end to end, and the
measured fwd+bwd time now BEATS the fused path on the real chip
(v5e, GPT-2-small shapes, causal, bf16, 8192 tokens/call):
1.3× at S=4096, 2.4× at S=8192, ~2.9× at S=16384 with the default
1024/1024 blocks (block sweep: 128→1024 monotonically faster; 2048²
tiles exceed VMEM).  LONGCTX.json carries the end-to-end training
crossover table.

**Shape generality (round 4).**  The wrapper pads S up to the next
multiple of 128 (tail keys masked to −∞ through the key-mask input,
tail query rows sliced off — their cotangent pads back as zeros) and D
up to the next multiple of 128 (zero columns cancel in the dot
products; the softmax scale stays 1/sqrt(D_original)), so EVERY shape
keeps the O(S·D)-backward kernel; the O(S²) fallbacks survive only
behind ``force_reference`` (tests).  General per-query masks
(broadcastable to (B, H, S, S)) stream through the kernels as an extra
(block_q, block_k) mask tile; pure key masks (B, 1, 1, S) keep the
cheaper (1, block_k) row layout.  Block sizes are capped at 512 when a
general mask or a padded D>128 head is present so the extra VMEM tile
fits.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
_LANES = 128  # row-stat scratch lane width (min f32 tile is (8, 128))


def _interpret():
    return jax.default_backend() == "cpu"  # no Mosaic on CPU (tests)


def _causal_skip(qi, kj, block_q, block_k):
    """True iff key block kj lies entirely above the causal diagonal of
    query block qi (first key position > last query position)."""
    return kj * block_k > qi * block_q + (block_q - 1)


def _band_skip(qi, kj, block_q, block_k, window):
    """True iff key block kj lies entirely BELOW the sliding-window
    band of query block qi (last key position < first query position −
    window + 1) — with causal+window the kernel touches only
    O(S·window) score tiles instead of O(S²/2)."""
    return kj * block_k + (block_k - 1) < qi * block_q - (window - 1)


def _block_run(qi, kj, block_q, block_k, causal, window):
    """Grid-level skip predicate shared by all four kernels."""
    run = True
    if causal:
        run = jnp.logical_not(_causal_skip(qi, kj, block_q, block_k))
        if window is not None:
            run = run & jnp.logical_not(
                _band_skip(qi, kj, block_q, block_k, window))
    return run


def _apply_causal(s, qi, kj, block_q, block_k, window=None):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    keep = q_pos >= k_pos
    if window is not None:  # band: query i sees keys [i-window+1, i]
        keep = keep & (q_pos - k_pos < window)
    return jnp.where(keep, s, NEG_INF)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, *rest, scale, causal,
                block_q, block_k, has_qmask, window=None):
    if has_qmask:
        qmask_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        qmask_ref = None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qi, kj = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = _block_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0]                                   # (block_q, D)
        k = k_ref[0]                                   # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = s + mask_ref[0, 0][None, :].astype(jnp.float32)
        if has_qmask:
            s = s + qmask_ref[0].astype(jnp.float32)
        if causal:
            s = _apply_causal(s, qi, kj, block_q, block_k,
                              window=window)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kj == n_k - 1)
    def _flush():
        l = l_ref[:, 0]
        # rows with zero mass (fully masked) emit 0, not NaN
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(l_safe)


def _qmask_specs(qdiv, qmod, block_q, block_k, swap=False):
    """BlockSpec for the (M, S, S) general-mask input.  ``(b // qdiv) %
    qmod`` maps the grid's B·H index onto the mask's leading dim without
    materializing broadcasts: M=1 → (1,1), M=B → (H,B), M=H → (1,H)
    (per-head bias like ALiBi stays H-sized in HBM), M=B·H → (1,B·H).
    ``swap=True`` for the dk/dv grid where the q block index is
    innermost."""
    if swap:
        return pl.BlockSpec((1, block_q, block_k),
                            lambda b, j, i: ((b // qdiv) % qmod, i, j))
    return pl.BlockSpec((1, block_q, block_k),
                        lambda b, i, j: ((b // qdiv) % qmod, i, j))


def _flash_fwd_pallas(q, k, v, mask, qmask, scale, causal, block_q,
                      block_k, qmap, window=None):
    """q,k,v: (BH, S, D); mask: (BH, S) additive key mask; qmask:
    optional (M, S, S) additive general mask addressed by qmap =
    (qdiv, qmod) (see _qmask_specs).  Returns (o, lse) with lse:
    (BH, 1, S) float32."""
    bh, s, d = q.shape
    grid = (bh, s // block_q, s // block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               has_qmask=qmask is not None,
                               window=window)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
    ]
    args = [q, k, v, mask[:, None, :]]
    if qmask is not None:
        in_specs.append(_qmask_specs(*qmap, block_q, block_k))
        args.append(qmask)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)


# --------------------------------------------------------------- backward


def _recompute_p(q, k, mask_row, qmask_tile, lse_row, qi, kj, scale,
                 causal, block_q, block_k, window=None):
    """Recompute the (block_q, block_k) probability tile from saved
    logsumexp: p = exp(s·scale + mask − lse)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    s = s + mask_row[None, :].astype(jnp.float32)
    if qmask_tile is not None:
        s = s + qmask_tile.astype(jnp.float32)
    if causal:
        s = _apply_causal(s, qi, kj, block_q, block_k, window=window)
    return jnp.exp(s - lse_row[:, None])


def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, delta_ref, lse_ref,
               *rest, scale, causal, block_q, block_k, has_qmask,
               window=None):
    if has_qmask:
        qmask_ref, dq_ref, dq_acc = rest
    else:
        qmask_ref = None
        dq_ref, dq_acc = rest
    qi, kj = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _block_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        p = _recompute_p(q, k, mask_ref[0, 0],
                         None if qmask_ref is None else qmask_ref[0],
                         lse_ref[0, 0], qi, kj,
                         scale, causal, block_q, block_k,
                         window=window)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_acc[:] = dq_acc[:] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    @pl.when(kj == n_k - 1)
    def _flush():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, delta_ref,
                lse_ref, *rest, scale, causal, block_q, block_k,
                has_qmask, window=None):
    if has_qmask:
        qmask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        qmask_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    kj, qi = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _block_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        p = _recompute_p(q, k, mask_ref[0, 0],
                         None if qmask_ref is None else qmask_ref[0],
                         lse_ref[0, 0], qi, kj,
                         scale, causal, block_q, block_k,
                         window=window)
        # dv += pᵀ·dO  — contract the query dim without materializing pᵀ
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, mask, qmask, o, lse, do, scale, causal,
                      block_q, block_k, qmap, dlse=None, window=None):
    bh, s, d = q.shape
    # δ = rowsum(dO ∘ O): one O(S·D) pass, shared by both kernels.
    # A direct cotangent on the logsumexp output enters the softmax
    # Jacobian as ds += p∘dlse, i.e. δ' = δ − dlse (ring attention's
    # partial-merge differentiates through lse).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]                     # (BH, 1, S)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    mask3 = mask[:, None, :]
    has_qmask = qmask is not None

    dq_kernel = functools.partial(_dq_kernel, scale=scale, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  has_qmask=has_qmask, window=window)
    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
    ]
    dq_args = [q, k, v, mask3, do, delta, lse]
    if has_qmask:
        dq_in_specs.append(_qmask_specs(*qmap, block_q, block_k))
        dq_args.append(qmask)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, s // block_q, s // block_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_args)

    dkv_kernel = functools.partial(_dkv_kernel, scale=scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, has_qmask=has_qmask,
                                   window=window)
    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
    ]
    dkv_args = [q, k, v, mask3, do, delta, lse]
    if has_qmask:
        dkv_in_specs.append(_qmask_specs(*qmap, block_q, block_k,
                                         swap=True))
        dkv_args.append(qmask)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, s // block_k, s // block_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_core(q, k, v, mask, qmask, scale, causal, block_q, block_k,
                qmap, window=None):
    """Differentiable (o, lse) pair — lse carries a real cotangent
    (ring attention's partial merge differentiates through it)."""
    return _flash_fwd_pallas(q, k, v, mask, qmask, scale, causal,
                             block_q, block_k, qmap, window=window)


def _flash_core_fwd(q, k, v, mask, qmask, scale, causal, block_q,
                    block_k, qmap, window=None):
    o, lse = _flash_fwd_pallas(q, k, v, mask, qmask, scale, causal,
                               block_q, block_k, qmap, window=window)
    return (o, lse), (q, k, v, mask, qmask, o, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, qmap, window,
                    res, cts):
    q, k, v, mask, qmask, o, lse = res
    do, dlse = cts
    dq, dk, dv = _flash_bwd_pallas(q, k, v, mask, qmask, o, lse, do,
                                   scale, causal, block_q, block_k,
                                   qmap, dlse=dlse, window=window)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _flash(q, k, v, mask, qmask, scale, causal, block_q, block_k,
           qmap, window=None):
    # o-only view: indexing the custom_vjp pair feeds dlse = 0
    return _flash_core(q, k, v, mask, qmask, scale, causal, block_q,
                       block_k, qmap, window)[0]


# ------------------------------------------------- non-kernel reference


def _blockwise_reference(q, k, v, mask, causal, block_k, window=None):
    """Numerically identical online-softmax attention built from a
    lax.scan over key blocks — kept as the ``force_reference`` oracle the
    kernel tests compare against.  NOTE its VJP reverses the scan by
    saving per-step residuals (O(S²) backward memory) — never selected
    automatically since the round-4 pad-to-block wrapper."""
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qs = q * scale
    num_kb = s // block_k
    k_blocks = k.reshape(bh, num_kb, block_k, d).transpose(1, 0, 2, 3)
    v_blocks = v.reshape(bh, num_kb, block_k, d).transpose(1, 0, 2, 3)
    m_blocks = mask.reshape(bh, num_kb, block_k).transpose(1, 0, 2)

    q_pos = jnp.arange(s)[None, :, None]  # (1, S, 1)

    def body(carry, inp):
        acc, m_prev, l_prev = carry
        kb_idx, kb, vb, mb = inp
        sc = jnp.einsum("bqd,bkd->bqk", qs, kb) + mb[:, None, :]
        if causal:
            k_pos = kb_idx * block_k + jnp.arange(block_k)[None, None, :]
            keep = q_pos >= k_pos
            if window is not None:
                keep = keep & (q_pos - k_pos < window)
            sc = jnp.where(keep, sc, NEG_INF)
        m_cur = jnp.max(sc, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(sc - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bqk,bkd->bqd", p, vb)
        return (acc, m_new, l_new), None

    init = (jnp.zeros((bh, s, d), jnp.float32),
            jnp.full((bh, s), NEG_INF, jnp.float32),
            jnp.zeros((bh, s), jnp.float32))
    (acc, m, l), _ = jax.lax.scan(
        body, init, (jnp.arange(num_kb), k_blocks, v_blocks, m_blocks))
    return (acc / l[..., None]).astype(q.dtype)


def _fused_reference(q, k, v, mask, causal, window=None):
    """Plain softmax(QKᵀ)V with the full (broadcast) mask, f32 compute —
    the ``force_reference`` oracle for general-mask shapes."""
    b, h, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    sc = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    if mask is not None:
        sc = sc + mask.astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((s, s), bool))
        if window is not None:
            i = jnp.arange(s)[:, None]
            j = jnp.arange(s)[None, :]
            cm = cm & (i - j < window)
        sc = jnp.where(cm[None, None], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# ----------------------------------------------------------- public API


def _fit_block(block, s):
    """Largest 128-multiple <= block that divides S (0 if none) — an S
    like 2560 must shrink to 512, not fall off the kernel onto the
    O(S²)-backward scan fallback; S is always padded to a 128-multiple
    first, so at least 128 fits."""
    block = min(block, s) // 128 * 128
    while block >= 128 and s % block != 0:
        block -= 128
    return block


def _key_mask_flat(mask, b, h, s):
    """(B,1,1,S) additive key mask -> (B·H, S) kernel layout, or None
    if the mask is not a pure key mask."""
    if mask is None:
        return None
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        return jnp.repeat(
            jnp.broadcast_to(mask[:, 0, 0, :], (b, s)), h, axis=0)
    return None


def _general_mask_flat(mask, b, h, s):
    """Additive mask broadcastable to (B, H, S, S) -> ((M, S, S),
    (qdiv, qmod)) where ``(bh // qdiv) % qmod`` maps the kernel's B·H
    grid index onto M, WITHOUT materializing the broadcast — a per-head
    bias like ALiBi's (1, H, S, S) stays H-sized in HBM.  (None, None)
    for layouts the kernel can't tile."""
    if mask.ndim == 2:
        mask = mask[None, None]
    if mask.ndim != 4:
        return None, None
    b0, h0 = mask.shape[0], mask.shape[1]
    if b0 not in (1, b) or h0 not in (1, h):
        return None, None
    mask = jnp.broadcast_to(mask, (b0, h0, s, s))
    if h0 == 1:
        # (1,1,S,S) -> (1, 1); (B,1,S,S) -> (H, B)
        return mask[:, 0], ((b * h) // b0 if b0 > 1 else b * h, b0)
    # (1,H,S,S) -> (1, H); (B,H,S,S) -> (1, B·H)
    return mask.reshape(b0 * h, s, s), (1, b0 * h)


def _pad_axis(x, target, axis, value=0.0):
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _prep_kernel(q, k, v, mask, block_q, block_k):
    """Pad to kernel-legal shapes and build the mask layouts.

    Returns ``(qf, kf, vf, mf, qmask, qmap, scale, bq, bk)`` with
    qf/kf/vf (B·H, S_pad, D_pad), mf (B·H, S_pad) f32 key mask (tail
    keys −∞), qmask optional (M, S_pad, S_pad), or None if the mask
    layout defeats the kernel (caller must use the fused reference)."""
    b, h, s, d = q.shape
    bh = b * h
    scale = 1.0 / math.sqrt(d)                # original D, not padded
    sp = -(-s // 128) * 128
    dp = max(128, -(-d // 128) * 128)

    mf_key = _key_mask_flat(mask, b, h, s)
    qmask, qmap = None, None
    if mask is not None and mf_key is None:
        qmask, qmap = _general_mask_flat(mask, b, h, s)
        if qmask is None:
            return None
    if qmask is not None:
        # the extra (block_q, block_k) mask tile needs VMEM headroom
        block_q, block_k = min(block_q, 512), min(block_k, 512)
    if dp > 128:
        # per-tile VMEM grows linearly with D (q/k/v/do tiles and the
        # f32 accumulator scratches are (block, D)); shrink the block
        # budget proportionally so wide heads still compile
        cap = max(128, (512 * 128 // dp) // 128 * 128)
        block_q, block_k = min(block_q, cap), min(block_k, cap)
    bq, bk = _fit_block(block_q, sp), _fit_block(block_k, sp)

    def flat_pad(x):
        x = x.reshape(bh, s, d)
        x = _pad_axis(x, sp, 1)
        return _pad_axis(x, dp, 2)

    qf, kf, vf = flat_pad(q), flat_pad(k), flat_pad(v)
    mf = jnp.zeros((bh, s), jnp.float32) if mf_key is None \
        else mf_key.astype(jnp.float32)
    mf = _pad_axis(mf, sp, 1, value=NEG_INF)  # tail keys masked out
    if qmask is not None:
        qmask = _pad_axis(_pad_axis(qmask, sp, 1), sp, 2)
    return qf, kf, vf, mf, qmask, qmap, scale, bq, bk


def flash_attention(q, k, v, mask=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    force_reference=False, window=None):
    """q,k,v: (B, H, S, D) raw jax arrays; mask: additive, broadcastable
    to (B, H, S, S) — key masks (B, 1, 1, S) take the cheap row layout,
    anything else streams as (block_q, block_k) tiles.  Any S and D are
    accepted (padded to kernel-legal shapes internally).  Returns
    (B, H, S, D)."""
    b, h, s, d = q.shape
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window requires causal=True and window >= 1 "
            f"(got causal={causal}, window={window}) — window<1 would "
            f"mask every in-band score to the finite NEG_INF floor and "
            f"silently return uniform attention")
    prep = None if force_reference else _prep_kernel(
        q, k, v, mask, block_q, block_k)
    if prep is None:
        mf = _key_mask_flat(mask, b, h, s)
        if mask is not None and mf is None:
            return _fused_reference(q, k, v, mask, causal,
                                    window=window)
        bk = _fit_block(block_k, s)
        if bk == 0:
            bk = s
        bh = b * h
        if mf is None:
            mf = jnp.zeros((bh, s), q.dtype)
        o = _blockwise_reference(q.reshape(bh, s, d), k.reshape(bh, s, d),
                                 v.reshape(bh, s, d), mf, causal, bk,
                                 window=window)
        return o.reshape(b, h, s, d)
    qf, kf, vf, mf, qmask, qmap, scale, bq, bk = prep
    o = _flash(qf, kf, vf, mf, qmask, scale, causal, bq, bk, qmap,
               window)
    return o[:, :s, :d].reshape(b, h, s, d)


def flash_attention_lse(q, k, v, mask=None, causal=False,
                        block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp (B, H, S) float32 — the quantity ring attention needs to
    merge per-shard partial attentions exactly.  Differentiable in both
    outputs (the lse cotangent folds into the softmax Jacobian).

    Kernel path for every shape since the round-4 padding wrapper; the
    fused-jnp fallback below survives only for mask layouts the kernel
    can't tile (non-broadcastable ndim)."""
    b, h, s, d = q.shape
    prep = _prep_kernel(q, k, v, mask, block_q, block_k)
    if prep is not None:
        qf, kf, vf, mf, qmask, qmap, scale, bq, bk = prep
        o, lse = _flash_core(qf, kf, vf, mf, qmask, scale, causal, bq,
                             bk, qmap)
        return (o[:, :s, :d].reshape(b, h, s, d),
                lse[:, 0, :s].reshape(b, h, s))
    # fallback: fused jnp with explicit logsumexp (jax autodiff)
    scale = 1.0 / math.sqrt(d)
    sc = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    if mask is not None:
        sc = sc + mask.astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((s, s), bool))
        sc = jnp.where(cm[None, None], sc, NEG_INF)
    # NEG_INF floor: -inf-masked full rows must yield p=0/lse=NEG_INF,
    # not exp(-inf - -inf) = NaN
    m = jnp.maximum(jnp.max(sc, axis=-1, keepdims=True), NEG_INF)
    p = jnp.exp(sc - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhst,bhtd->bhsd", p / l_safe,
                   v.astype(jnp.float32))
    return o.astype(q.dtype), (m + jnp.log(l_safe))[..., 0]


def flash_attention_op(q, k, v, mask=None, causal=False, remat=False,
                       window=None):
    """Tensor-level autograd op (used by ops/attention.py and the
    tensor_parallel flash path).

    Recorded as ``TPAttention`` with the same ``scale``/``causal``
    params as the fused path: the kernel computes the identical math
    (scale = 1/sqrt(D) internally), so sonnx's decomposed attention
    export covers flash-built models too.  ``remat`` is accepted for
    API symmetry with the fused path but is a no-op here: the kernel
    backward already recomputes probabilities blockwise from the saved
    logsumexp, so there is no S×S residual to rematerialize away
    (wrapping in jax.checkpoint would only re-run the forward kernel
    for zero memory gain)."""
    del remat
    from ...autograd import _op  # local import, no cycles

    scale = 1.0 / math.sqrt(q.shape[-1])
    if mask is None:
        return _op(
            lambda qv, kv, vv, scale, causal, window: flash_attention(
                qv, kv, vv, causal=causal, window=window),
            q, k, v, _name="TPAttention", scale=scale, causal=causal,
            window=window)
    return _op(
        lambda qv, kv, vv, mv, scale, causal, window: flash_attention(
            qv, kv, vv, mv, causal=causal, window=window),
        q, k, v, mask, _name="TPAttention", scale=scale, causal=causal,
        window=window)
