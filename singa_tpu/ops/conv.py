"""Convolution (reference: src/model/operation/convolution.{h,cc},
unverified — ``ConvHandle``/``CudnnConvHandle`` + ``GpuConvForward`` /
``GpuConvBackwardx/W`` cuDNN calls, CPU im2col+GEMM fallback).

TPU-native: one ``lax.conv_general_dilated`` in NCHW/OIHW layout; XLA
lowers it onto the MXU and autodiff provides the backward-data /
backward-filter convs the reference hand-wires to cuDNN.  The handle
structs disappear — algorithm selection and workspace management are
XLA's job.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import amp
from ..autograd import _op
from .padding import resolve as _resolve_padding


def conv2d(x, W, b=None, stride=(1, 1), padding=(0, 0), dilation=(1, 1),
           group=1, pad_mode="NOTSET"):
    """NCHW conv; W is OIHW (O = out channels, I = in/group).

    ``padding`` accepts per-dim symmetric ints or explicit (lo, hi)
    pairs (asymmetric ONNX pads import as the latter); SAME modes are
    resolved ONNX-style from input size + stride (ops/padding.py).
    """
    kernel = W.shape[2:]
    pads = _resolve_padding(pad_mode, padding, x.shape[2:], kernel,
                            stride, dilation)

    def f(xv, wv, *rest, stride=tuple(stride), pads=pads,
          dilation=tuple(dilation), group=int(group)):
        xv, wv = amp.cast_in(xv, wv)  # bf16 on the MXU under amp
        y = lax.conv_general_dilated(
            xv, wv,
            window_strides=stride,
            padding=pads,
            rhs_dilation=dilation,
            feature_group_count=group,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if rest:
            y = y + amp.cast_in(rest[0])[None, :, None, None]
        return y

    # pass the geometry through _op's params so the op instance carries it
    # (sonnx export reads op.params for node attributes)
    kw = dict(stride=tuple(stride), pads=pads, dilation=tuple(dilation),
              group=int(group))
    if b is None:
        return _op(f, x, W, _name="Conv2d", **kw)
    return _op(f, x, W, b, _name="Conv2d", **kw)
