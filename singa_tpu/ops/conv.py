"""Convolution (reference: src/model/operation/convolution.{h,cc},
unverified — ``ConvHandle``/``CudnnConvHandle`` + ``GpuConvForward`` /
``GpuConvBackwardx/W`` cuDNN calls, CPU im2col+GEMM fallback).

TPU-native: one ``lax.conv_general_dilated`` in NCHW/OIHW layout; XLA
lowers it onto the MXU and autodiff provides the backward-data /
backward-filter convs the reference hand-wires to cuDNN.  The handle
structs disappear — algorithm selection and workspace management are
XLA's job.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import amp
from ..autograd import _op
from .padding import resolve as _resolve_padding


def conv2d(x, W, b=None, stride=1, padding=0, dilation=1,
           group=1, pad_mode="NOTSET"):
    """N-dimensional conv over channels-first layout (NC + spatial; the
    2-D case is the reference's NCHW/OIHW).  The name keeps the
    reference API; the rank comes from the input, so ONNX Conv imports
    with 1-D or 3-D kernels route through the same op (sonnx._h_conv).

    ``padding`` accepts per-dim symmetric ints or explicit (lo, hi)
    pairs (asymmetric ONNX pads import as the latter); SAME modes are
    resolved ONNX-style from input size + stride (ops/padding.py).
    """
    kernel = W.shape[2:]
    n = len(kernel)
    assert x.shape[2:] and len(x.shape[2:]) == n, (
        f"input rank {len(x.shape)} does not match kernel rank {n + 2}")
    stride = _tup(stride, n)
    dilation = _tup(dilation, n)
    if not isinstance(padding, (tuple, list)):
        padding = (padding,) * n
    assert len(padding) == n, (
        f"expected {n} padding entries (ints or (lo,hi) pairs), "
        f"got {padding}")
    pads = _resolve_padding(pad_mode, padding, x.shape[2:], kernel,
                            stride, dilation)
    # channels-first numeric dim spec for any spatial rank
    spec = (0, 1) + tuple(range(2, 2 + n))
    dnums = lax.ConvDimensionNumbers(lhs_spec=spec, rhs_spec=spec,
                                     out_spec=spec)

    def f(xv, wv, *rest, stride=stride, pads=pads, dilation=dilation,
          group=int(group)):
        xv, wv = amp.cast_in(xv, wv)  # bf16 on the MXU under amp
        y = lax.conv_general_dilated(
            xv, wv,
            window_strides=stride,
            padding=pads,
            rhs_dilation=dilation,
            feature_group_count=group,
            dimension_numbers=dnums,
        )
        if rest:
            bshape = (1, -1) + (1,) * n
            y = y + amp.cast_in(rest[0]).reshape(bshape)
        return y

    # pass the geometry through _op's params so the op instance carries it
    # (sonnx export reads op.params for node attributes)
    kw = dict(stride=stride, pads=pads, dilation=dilation, group=int(group))
    if b is None:
        return _op(f, x, W, _name="Conv2d", **kw)
    return _op(f, x, W, b, _name="Conv2d", **kw)


def conv_transpose2d(x, W, b=None, stride=1, padding=0, dilation=1,
                     group=1, output_padding=0):
    """Transposed (fractionally-strided) convolution, channels-first,
    any spatial rank — the backward-data conv exposed as a forward op
    (the reference wires cuDNN's ConvolutionBackwardData; here it is
    one ``lax.conv_general_dilated`` with lhs_dilation = stride and a
    spatially-flipped, group-transposed kernel, which XLA lowers onto
    the MXU like any conv).

    ``W`` uses the ONNX/torch ConvTranspose layout
    (C_in, C_out/group, *kernel).  Output spatial size per dim:
    (in-1)*stride - pad_lo - pad_hi + (k-1)*dilation + 1 + output_padding.
    """
    kernel = W.shape[2:]
    n = len(kernel)
    assert x.shape[2:] and len(x.shape[2:]) == n, (
        f"input rank {len(x.shape)} does not match kernel rank {n + 2}")
    stride = _tup(stride, n)
    dilation = _tup(dilation, n)
    output_padding = _tup(output_padding, n)
    if not isinstance(padding, (tuple, list)):
        padding = (padding,) * n
    pads = tuple(p if isinstance(p, (tuple, list)) else (int(p), int(p))
                 for p in padding)
    assert len(pads) == n
    keff = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilation))
    # transposed-conv padding identity: lo' = k_eff-1-lo (negative pads
    # crop, which lax accepts), plus output_padding on the high edge
    tpads = tuple((ke - 1 - lo, ke - 1 - hi + op)
                  for ke, (lo, hi), op in zip(keff, pads, output_padding))
    spec = (0, 1) + tuple(range(2, 2 + n))
    dnums = lax.ConvDimensionNumbers(lhs_spec=spec, rhs_spec=spec,
                                     out_spec=spec)
    g = int(group)

    def f(xv, wv, *rest, stride=stride, pads=pads, dilation=dilation,
          group=g, output_padding=output_padding, tpads=tpads):
        xv, wv = amp.cast_in(xv, wv)
        cin, cog = wv.shape[0], wv.shape[1]
        # (C_in, C_out/g, k) -> (C_out, C_in/g, k): group i of the
        # output reads group i of the input (transposed-conv grouping)
        w = wv.reshape((group, cin // group, cog) + tuple(kernel))
        w = jnp.swapaxes(w, 1, 2).reshape(
            (group * cog, cin // group) + tuple(kernel))
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        y = lax.conv_general_dilated(
            xv, w,
            window_strides=(1,) * n,
            padding=tpads,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            feature_group_count=group,
            dimension_numbers=dnums,
        )
        if rest:
            bshape = (1, -1) + (1,) * n
            y = y + amp.cast_in(rest[0]).reshape(bshape)
        return y

    kw = dict(stride=stride, pads=pads, dilation=dilation, group=g,
              output_padding=output_padding)
    if b is None:
        return _op(f, x, W, _name="ConvTranspose2d", **kw)
    return _op(f, x, W, b, _name="ConvTranspose2d", **kw)


def _tup(v, n):
    if isinstance(v, (tuple, list)):
        assert len(v) == n, f"expected {n} values, got {v}"
        return tuple(int(s) for s in v)
    return (int(v),) * n
