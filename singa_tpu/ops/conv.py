"""Convolution (reference: src/model/operation/convolution.{h,cc},
unverified — ``ConvHandle``/``CudnnConvHandle`` + ``GpuConvForward`` /
``GpuConvBackwardx/W`` cuDNN calls, CPU im2col+GEMM fallback).

TPU-native: one ``lax.conv_general_dilated`` in NCHW/OIHW layout; XLA
lowers it onto the MXU and autodiff provides the backward-data /
backward-filter convs the reference hand-wires to cuDNN.  The handle
structs disappear — algorithm selection and workspace management are
XLA's job.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..autograd import _op


def _resolve_padding(pad_mode, padding, kernel, dilation):
    if pad_mode in ("SAME_UPPER", "SAME_LOWER", "SAME"):
        pads = []
        for k, d in zip(kernel, dilation):
            eff = d * (k - 1)
            lo = eff // 2
            hi = eff - lo
            if pad_mode == "SAME_LOWER":
                lo, hi = hi, lo
            pads.append((lo, hi))
        return tuple(pads)
    if pad_mode == "VALID":
        return ((0, 0), (0, 0))
    return tuple((p, p) for p in padding)


def conv2d(x, W, b=None, stride=(1, 1), padding=(0, 0), dilation=(1, 1),
           group=1, pad_mode="NOTSET"):
    """NCHW conv; W is OIHW (O = out channels, I = in/group)."""
    kernel = W.shape[2:]
    pads = _resolve_padding(pad_mode, padding, kernel, dilation)

    def f(xv, wv, *rest, stride=tuple(stride), pads=pads,
          dilation=tuple(dilation), group=int(group)):
        y = lax.conv_general_dilated(
            xv, wv,
            window_strides=stride,
            padding=pads,
            rhs_dilation=dilation,
            feature_group_count=group,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if rest:
            y = y + rest[0][None, :, None, None]
        return y

    # pass the geometry through _op's params so the op instance carries it
    # (sonnx export reads op.params for node attributes)
    kw = dict(stride=tuple(stride), pads=pads, dilation=tuple(dilation),
              group=int(group))
    if b is None:
        return _op(f, x, W, _name="Conv2d", **kw)
    return _op(f, x, W, b, _name="Conv2d", **kw)
