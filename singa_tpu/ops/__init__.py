"""Op kernels — the rebuild of the reference's ``src/model/operation/``
(cuDNN handle kernels, unverified): conv, batchnorm, pooling, rnn,
attention; plus Pallas custom kernels under ``ops/pallas/``."""
