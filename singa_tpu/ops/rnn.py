"""Recurrent ops (reference: src/model/operation/rnn.{h,cc}, unverified —
``CudnnRNNHandle``: packed single-buffer weight layout, LSTM/GRU/
vanilla-tanh/relu modes, multi-layer, bidirectional, inter-layer dropout).

TPU-native: each layer-direction is one ``lax.scan`` over time whose cell
is a fused GEMM (both input and recurrent projections hit the MXU);
``jax.vjp`` through the scan replaces cuDNN's rnn-backward.  The
cuDNN-style *packed weight* API is kept: all weights live in ONE flat
parameter (``RNNHandle.weights_size``), as the reference exposes, so
checkpoints and DistOpt treat an RNN as a single tensor.

Layout of the packed buffer (documented here since cuDNN's is opaque):
for each layer, for each direction: W_ih (G*H, I), W_hh (G*H, H),
b_ih (G*H,), b_hh (G*H,), flattened row-major and concatenated.
Gate order: LSTM i,f,g,o; GRU r,z,n (cuDNN convention).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from .. import amp
from ..autograd import _Func
from ..layer import Layer
from ..tensor import Tensor

_GATES = {"lstm": 4, "gru": 3, "vanilla_tanh": 1, "vanilla_relu": 1}


class RNNHandle:
    """Parity stand-in for CudnnRNNHandle: computes the packed weight size
    and the per-(layer, direction) slice offsets.

    The round-1..3 Pallas fused-cell LSTM kernel was DELETED in round 4
    after the decisive sweep (real v5e, on-device loop differencing):
    at the char-RNN bench shape it could not fit VMEM at all (T·B·4H
    floats must be resident) and silently fell back to a hoisted-GEMM
    scan that tied the plain scan (5108 vs 4816 samples/s, overlapping
    spreads); at every VMEM-fitting shape (T≤20) both paths run in
    tens of microseconds and the kernel LOSES or ties (0.32x–1.23x,
    all within tunnel noise).  lax.scan + XLA is the one
    measurement-backed path.  ``use_pallas`` is still accepted (and
    ignored) for checkpoint/API compatibility."""

    def __init__(self, input_size, hidden_size, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, use_pallas=False):
        assert mode in _GATES, f"unknown rnn mode {mode}"
        self.input_size = int(input_size)
        self.hidden_size = int(hidden_size)
        self.num_layers = int(num_layers)
        self.mode = mode
        self.bidirectional = bool(bidirectional)
        self.num_directions = 2 if bidirectional else 1
        self.dropout = float(dropout)
        del use_pallas  # accepted for API compat; kernel deleted (round 4)
        self.slices = self._layout()
        self.weights_size = self._total

    def _layout(self):
        G, H = _GATES[self.mode], self.hidden_size
        off = 0
        slices = {}
        for l in range(self.num_layers):
            I = self.input_size if l == 0 else H * self.num_directions
            for d in range(self.num_directions):
                for name, shape in (("w_ih", (G * H, I)), ("w_hh", (G * H, H)),
                                    ("b_ih", (G * H,)), ("b_hh", (G * H,))):
                    n = int(np.prod(shape))
                    slices[(l, d, name)] = (off, off + n, shape)
                    off += n
        self._total = off
        return slices

    def unpack(self, w_flat, l, d):
        out = {}
        for name in ("w_ih", "w_hh", "b_ih", "b_hh"):
            a, b, shape = self.slices[(l, d, name)]
            out[name] = w_flat[a:b].reshape(shape)
        return out

    def init_weights(self, device, dtype=jnp.float32) -> Tensor:
        """One flat weight tensor, uniform(-1/sqrt(H), 1/sqrt(H)) like
        cuDNN-era SINGA init."""
        w = Tensor((self.weights_size,), device=device, dtype=dtype,
                   requires_grad=True, stores_grad=True)
        k = 1.0 / np.sqrt(self.hidden_size)
        w.uniform(-k, k)
        return w


def _cell_fn(mode):
    if mode == "lstm":
        def cell(carry, xt, w_ih, w_hh, b):
            h, c = carry
            g = xt @ w_ih.T + h @ w_hh.T + b
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            gg = jnp.tanh(gg)
            c = f * c + i * gg
            h = o * jnp.tanh(c)
            return (h, c), h
        return cell
    if mode == "gru":
        def cell(carry, xt, w_ih, w_hh, b_ih, b_hh):
            h, = carry
            gi = xt @ w_ih.T + b_ih
            gh = h @ w_hh.T + b_hh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn)
            h = (1 - z) * n + z * h
            return (h,), h
        return cell
    act = jnp.tanh if mode == "vanilla_tanh" else jax.nn.relu

    def cell(carry, xt, w_ih, w_hh, b):
        h, = carry
        h = act(xt @ w_ih.T + h @ w_hh.T + b)
        return (h,), h
    return cell


def _scan_direction(x, h0, c0, params, mode, reverse):
    """x: (T, B, I) -> y: (T, B, H); returns (y, h_T, c_T)."""
    cell = _cell_fn(mode)
    if mode == "gru":
        def f(carry, xt):
            return cell(carry, xt, params["w_ih"], params["w_hh"],
                        params["b_ih"], params["b_hh"])
        carry0 = (h0,)
    else:
        b = params["b_ih"] + params["b_hh"]
        def f(carry, xt):
            return cell(carry, xt, params["w_ih"], params["w_hh"], b)
        carry0 = (h0, c0) if mode == "lstm" else (h0,)
    carry, ys = lax.scan(f, carry0, x, reverse=reverse)
    h_T = carry[0]
    c_T = carry[1] if mode == "lstm" else jnp.zeros_like(h_T)
    return ys, h_T, c_T


def rnn_forward(x, hx, cx, W, handle, batch_first=False):
    """Full multi-layer (bi)directional RNN as autograd ops.

    x: Tensor (T,B,I) or (B,T,I) if batch_first; hx/cx: Tensors
    (L*D, B, H); W: packed flat weight Tensor.
    Returns (y, hy, cy) Tensors; for non-LSTM modes cy is zeros.
    """
    mode = handle.mode
    L, D, H = handle.num_layers, handle.num_directions, handle.hidden_size

    if batch_first:
        x = autograd.transpose(x, (1, 0, 2))

    inp = x
    h_finals, c_finals = [], []
    for l in range(L):
        outs = []
        for d in range(D):
            idx = l * D + d

            def f(xv, hv, cv, wv, l=l, d=d, idx=idx, **_meta):
                params = handle.unpack(wv, l, d)
                y, hT, cT = _scan_direction(
                    xv, hv[idx], cv[idx], params, mode, reverse=(d == 1))
                return y, hT, cT

            # slice metadata rides op.params so sonnx export can unpack
            # the flat weight into ONNX W/R/B initializers (_dec_rnn)
            y, hT, cT = _Func(
                fn=f, name=f"RNN[l{l}d{d}]",
                mode=mode, layer=l, direction=d, idx=idx, hidden=H,
                slices={name: handle.slices[(l, d, name)]
                        for name in ("w_ih", "w_hh", "b_ih", "b_hh")},
            )(inp, hx, cx, W)
            outs.append(y)
            h_finals.append(hT)
            c_finals.append(cT)
        inp = outs[0] if D == 1 else autograd.cat(outs, axis=2)
        if handle.dropout > 0 and l < L - 1:
            inp = autograd.dropout(inp, handle.dropout)

    y = inp
    if batch_first:
        y = autograd.transpose(y, (1, 0, 2))
    hy = autograd.cat([autograd.unsqueeze(t, 0) for t in h_finals], axis=0) \
        if len(h_finals) > 1 else autograd.unsqueeze(h_finals[0], 0)
    cy = autograd.cat([autograd.unsqueeze(t, 0) for t in c_finals], axis=0) \
        if len(c_finals) > 1 else autograd.unsqueeze(c_finals[0], 0)
    return y, hy, cy


class _BaseRNN(Layer):
    """Shared layer wrapper over rnn_forward with the packed-weight
    handle (reference: layer.CudnnRNN / autograd RNN classes)."""

    mode = "vanilla_tanh"

    def __init__(self, hidden_size, num_layers=1, bidirectional=False,
                 dropout=0.0, batch_first=False, return_sequences=True,
                 use_pallas=False):  # accepted+ignored (round 4)
        super().__init__()
        self.hidden_size = int(hidden_size)
        self.num_layers = int(num_layers)
        self.bidirectional = bool(bidirectional)
        self.dropout = float(dropout)
        self.batch_first = bool(batch_first)
        self.return_sequences = return_sequences
        del use_pallas
        self.handle = None

    def initialize(self, x, hx=None, cx=None):
        input_size = x.shape[-1]
        self.handle = RNNHandle(
            input_size, self.hidden_size, self.num_layers, self.mode,
            self.bidirectional, self.dropout)
        self.W = self.handle.init_weights(x.device, amp.param_dtype(x.data.dtype))

    def _zero_state(self, x):
        B = x.shape[0] if self.batch_first else x.shape[1]
        L, D, H = self.num_layers, self.handle.num_directions, self.hidden_size
        z = Tensor((L * D, B, H), device=x.device, dtype=x.data.dtype,
                   requires_grad=False)
        return z

    def forward(self, x, hx=None, cx=None):
        if hx is None:
            hx = self._zero_state(x)
        if cx is None:
            cx = self._zero_state(x)
        y, hy, cy = rnn_forward(x, hx, cx, self.W, self.handle,
                                self.batch_first)
        if self.mode == "lstm":
            return (y, (hy, cy)) if self.return_sequences else (hy, (hy, cy))
        return (y, hy) if self.return_sequences else (hy, hy)


class LSTM(_BaseRNN):
    mode = "lstm"


class GRU(_BaseRNN):
    mode = "gru"


class RNN(_BaseRNN):
    """Vanilla RNN; nonlinearity in {'tanh','relu'} (reference arg)."""

    def __init__(self, hidden_size, nonlinearity="tanh", **kw):
        super().__init__(hidden_size, **kw)
        self.mode = f"vanilla_{nonlinearity}"


class CudnnRNN(LSTM):
    """Source-compat alias: the reference exposes the cuDNN-backed RNN
    under this name; here it is the same scan-based LSTM."""
