"""Pooling (reference: src/model/operation/pooling.{h,cc}, unverified —
``PoolingHandle`` max/avg cuDNN fwd/bwd).

TPU-native: ``lax.reduce_window``; autodiff of the max window reduce is
XLA's select-and-scatter, replacing cuDNN's pooling-backward kernel.
Average pooling divides by the full window size (count-include-pad,
matching cuDNN's default mode used by the reference).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..autograd import _op
from .padding import resolve as _resolve_padding


def pooling2d(x, kernel, stride, padding=(0, 0), is_max=True,
              pad_mode="NOTSET"):
    """``padding`` is either per-dim symmetric ints or explicit (lo, hi)
    pairs (the latter is what asymmetric ONNX pads import as); SAME
    modes are resolved ONNX-style from input size + stride."""
    spatial = _resolve_padding(pad_mode, padding, x.shape[2:], kernel,
                               stride)
    pads = ((0, 0), (0, 0)) + tuple(spatial)

    # geometry rides op.params (sonnx export reads it — see autograd._op);
    # pads_pairs carries the resolved (lo, hi) per spatial dim so export
    # round-trips asymmetric SAME padding exactly
    kw = dict(kernel=tuple(kernel), stride=tuple(stride),
              pads_pairs=tuple(spatial))

    if is_max:
        def f(xv, kernel, stride, pads_pairs, pads=pads):
            return lax.reduce_window(
                xv, -jnp.inf, lax.max, (1, 1) + kernel, (1, 1) + stride, pads)

        return _op(f, x, _name="MaxPool2d", **kw)

    wsize = float(np.prod(kernel))

    def f(xv, kernel, stride, pads_pairs, pads=pads, wsize=wsize):
        s = lax.reduce_window(xv, 0.0, lax.add, (1, 1) + kernel,
                              (1, 1) + stride, pads)
        return s / wsize

    return _op(f, x, _name="AvgPool2d", **kw)
