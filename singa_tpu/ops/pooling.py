"""Pooling (reference: src/model/operation/pooling.{h,cc}, unverified —
``PoolingHandle`` max/avg cuDNN fwd/bwd).

TPU-native: ``lax.reduce_window``; autodiff of the max window reduce is
XLA's select-and-scatter, replacing cuDNN's pooling-backward kernel.
Average pooling divides by the full window size (count-include-pad,
matching cuDNN's default mode used by the reference).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..autograd import _op


def pooling2d(x, kernel, stride, padding=(0, 0), is_max=True,
              pad_mode="NOTSET"):
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    if pad_mode in ("SAME", "SAME_UPPER", "SAME_LOWER"):
        spatial = []
        for k in kernel:
            lo = (k - 1) // 2
            hi = (k - 1) - lo
            if pad_mode == "SAME_LOWER":
                lo, hi = hi, lo
            spatial.append((lo, hi))
        pads = ((0, 0), (0, 0)) + tuple(spatial)
    else:
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)

    if is_max:
        def f(xv):
            return lax.reduce_window(
                xv, -jnp.inf, lax.max, window, strides, pads)

        return _op(f, x, _name="MaxPool2d")

    wsize = float(np.prod(kernel))

    def f(xv):
        s = lax.reduce_window(xv, 0.0, lax.add, window, strides, pads)
        return s / wsize

    return _op(f, x, _name="AvgPool2d")
