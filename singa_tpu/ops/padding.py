"""Shared ONNX ``auto_pad`` resolution (used by conv and pooling).

ONNX SAME_UPPER/SAME_LOWER pads depend on the input spatial size and the
stride, not just the kernel: ``out = ceil(in / stride)`` and
``total = max(0, (out-1)*stride + eff_kernel - in)``, split low/high with
the odd element going to the end (SAME_UPPER) or the beginning
(SAME_LOWER).  (Reference behavior: cuDNN handles SAME via the framework
computing explicit pads the same way; see SURVEY.md §2.1 Conv op row.)
"""

from __future__ import annotations


def same_pads(in_size, kernel, stride, dilation=None, lower=False):
    """Per-spatial-dim (lo, hi) explicit pads for ONNX SAME auto_pad."""
    if dilation is None:
        dilation = (1,) * len(kernel)
    pairs = []
    for i, k, s, d in zip(in_size, kernel, stride, dilation):
        eff = d * (k - 1) + 1
        out = -(-int(i) // int(s))  # ceil division
        total = max(0, (out - 1) * s + eff - int(i))
        lo = total // 2
        hi = total - lo
        pairs.append((hi, lo) if lower else (lo, hi))
    return tuple(pairs)


def as_pairs(padding):
    """Normalize ``padding`` — per-dim ints or explicit (lo, hi) pairs —
    to a tuple of (lo, hi) pairs."""
    return tuple(tuple(p) if isinstance(p, (tuple, list)) else (int(p), int(p))
                 for p in padding)


def resolve(pad_mode, padding, in_size, kernel, stride, dilation=None):
    """Resolve (pad_mode, padding) to explicit (lo, hi) pairs."""
    if pad_mode in ("SAME", "SAME_UPPER", "SAME_LOWER"):
        return same_pads(in_size, kernel, stride, dilation,
                         lower=pad_mode == "SAME_LOWER")
    if pad_mode == "VALID":
        return tuple((0, 0) for _ in kernel)
    return as_pairs(padding)
