"""Minimal pure-Python ONNX protobuf codec.

The reference's ``sonnx.py`` depends on the ``onnx`` pip package; this
container has no network and no ``onnx`` wheel (SURVEY.md §7 step 7), so
the stable subset of onnx.proto3 needed for model import/export is
implemented directly over the protobuf wire format: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto,
TypeProto, OperatorSetIdProto.

Wire format: each field is a varint key ``(field_number << 3) | wire_type``
with wire types 0=varint, 1=fixed64, 2=length-delimited, 5=fixed32.
Field numbers below are from the public onnx.proto3 (stable across ONNX
releases; the IR is forward-compatible by design).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

# -- ONNX TensorProto.DataType enum ----------------------------------------
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = range(1, 10)
FLOAT16, DOUBLE, UINT32, UINT64, COMPLEX64, COMPLEX128, BFLOAT16 = range(10, 17)

DTYPE_TO_NP = {
    FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8, UINT16: np.uint16,
    INT16: np.int16, INT32: np.int32, INT64: np.int64, BOOL: np.bool_,
    FLOAT16: np.float16, DOUBLE: np.float64, UINT32: np.uint32,
    UINT64: np.uint64,
}
NP_TO_DTYPE = {np.dtype(v): k for k, v in DTYPE_TO_NP.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


# ---------------------------------------------------------------------------
# wire-level primitives
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out, value):
    if value < 0:
        value += 1 << 64  # two's complement for negative int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _signed(value):
    """Interpret a 64-bit varint as signed int64."""
    return value - (1 << 64) if value >= (1 << 63) else value


def _fields(buf):
    """Iterate (field_number, wire_type, value) over a message buffer."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _emit(out, fnum, wtype, payload):
    _write_varint(out, (fnum << 3) | wtype)
    if wtype == 0:
        _write_varint(out, payload)
    elif wtype == 2:
        _write_varint(out, len(payload))
        out.extend(payload)
    else:
        out.extend(payload)


def _packed_or_repeated_varints(buf, wtype, val, signed=True):
    """Handle repeated int64 fields that may arrive packed (wtype 2)."""
    if wtype == 0:
        return [_signed(val) if signed else val]
    vals, pos = [], 0
    while pos < len(val):
        v, pos = _read_varint(val, pos)
        vals.append(_signed(v) if signed else v)
    return vals


# ---------------------------------------------------------------------------
# message dataclasses (subset mirroring onnx.proto3)
# ---------------------------------------------------------------------------

@dataclass
class TensorProto:
    name: str = ""
    dims: list = field(default_factory=list)
    data_type: int = FLOAT
    raw_data: bytes = b""
    float_data: list = field(default_factory=list)
    int32_data: list = field(default_factory=list)
    int64_data: list = field(default_factory=list)

    # field numbers: dims=1 data_type=2 float_data=4 int32_data=5
    # string_data=6 int64_data=7 name=8 raw_data=9
    @classmethod
    def parse(cls, buf):
        t = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                t.dims.extend(_packed_or_repeated_varints(buf, wtype, val))
            elif fnum == 2:
                t.data_type = val
            elif fnum == 4:
                if wtype == 5:
                    t.float_data.append(struct.unpack("<f", val)[0])
                else:
                    t.float_data.extend(
                        struct.unpack(f"<{len(val) // 4}f", val))
            elif fnum == 5:
                t.int32_data.extend(_packed_or_repeated_varints(buf, wtype, val))
            elif fnum == 7:
                t.int64_data.extend(_packed_or_repeated_varints(buf, wtype, val))
            elif fnum == 8:
                t.name = val.decode()
            elif fnum == 9:
                t.raw_data = bytes(val)
        return t

    def serialize(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            _emit(out, 1, 0, int(d))
        _emit(out, 2, 0, self.data_type)
        if self.name:
            _emit(out, 8, 2, self.name.encode())
        if self.raw_data:
            _emit(out, 9, 2, self.raw_data)
        return bytes(out)

    def to_numpy(self) -> np.ndarray:
        np_dtype = DTYPE_TO_NP[self.data_type]
        shape = tuple(self.dims)
        if self.raw_data:
            return np.frombuffer(self.raw_data, dtype=np_dtype).reshape(shape).copy()
        if self.float_data:
            return np.asarray(self.float_data, np.float32).reshape(shape)
        if self.int64_data:
            return np.asarray(self.int64_data, np.int64).reshape(shape)
        if self.int32_data:
            return np.asarray(self.int32_data, np_dtype).reshape(shape)
        return np.zeros(shape, np_dtype)

    @classmethod
    def from_numpy(cls, arr: np.ndarray, name=""):
        arr = np.asarray(arr)
        # NB: ascontiguousarray promotes 0-d to (1,) — keep the true
        # shape for dims (scalar initializers matter: a Gather with a
        # 0-d index drops the axis, with a (1,) index it doesn't)
        data = np.ascontiguousarray(arr)
        return cls(name=name, dims=list(arr.shape),
                   data_type=NP_TO_DTYPE[arr.dtype], raw_data=data.tobytes())


@dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: "TensorProto | None" = None
    g: "GraphProto | None" = None  # If/Loop/Scan subgraph bodies
    floats: list = field(default_factory=list)
    ints: list = field(default_factory=list)
    strings: list = field(default_factory=list)

    # name=1 f=2 i=3 s=4 t=5 g=6 floats=7 ints=8 strings=9 type=20
    @classmethod
    def parse(cls, buf):
        a = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                a.name = val.decode()
            elif fnum == 2:
                a.f = struct.unpack("<f", val)[0]
            elif fnum == 3:
                a.i = _signed(val)
            elif fnum == 4:
                a.s = bytes(val)
            elif fnum == 5:
                a.t = TensorProto.parse(val)
            elif fnum == 6:
                a.g = GraphProto.parse(val)
            elif fnum == 7:
                if wtype == 5:
                    a.floats.append(struct.unpack("<f", val)[0])
                else:
                    a.floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            elif fnum == 8:
                a.ints.extend(_packed_or_repeated_varints(buf, wtype, val))
            elif fnum == 9:
                a.strings.append(bytes(val))
            elif fnum == 20:
                a.type = val
        return a

    def serialize(self) -> bytes:
        out = bytearray()
        _emit(out, 1, 2, self.name.encode())
        if self.type == ATTR_FLOAT:
            _emit(out, 2, 5, struct.pack("<f", self.f))
        elif self.type == ATTR_INT:
            _emit(out, 3, 0, self.i)
        elif self.type == ATTR_STRING:
            _emit(out, 4, 2, self.s)
        elif self.type == ATTR_TENSOR:
            _emit(out, 5, 2, self.t.serialize())
        elif self.type == ATTR_GRAPH:
            _emit(out, 6, 2, self.g.serialize())
        elif self.type == ATTR_FLOATS:
            for v in self.floats:
                _emit(out, 7, 5, struct.pack("<f", v))
        elif self.type == ATTR_INTS:
            for v in self.ints:
                _emit(out, 8, 0, int(v))
        elif self.type == ATTR_STRINGS:
            for v in self.strings:
                _emit(out, 9, 2, v)
        _emit(out, 20, 0, self.type)
        return bytes(out)

    def value(self):
        return {
            ATTR_FLOAT: self.f, ATTR_INT: self.i, ATTR_STRING: self.s.decode(),
            ATTR_TENSOR: self.t, ATTR_GRAPH: self.g,
            ATTR_FLOATS: list(self.floats),
            ATTR_INTS: list(self.ints),
            ATTR_STRINGS: [s.decode() for s in self.strings],
        }.get(self.type)

    @classmethod
    def make(cls, name, value):
        a = cls(name=name)
        if isinstance(value, float):
            a.type, a.f = ATTR_FLOAT, value
        elif isinstance(value, bool):
            a.type, a.i = ATTR_INT, int(value)
        elif isinstance(value, int):
            a.type, a.i = ATTR_INT, value
        elif isinstance(value, str):
            a.type, a.s = ATTR_STRING, value.encode()
        elif isinstance(value, TensorProto):
            a.type, a.t = ATTR_TENSOR, value
        elif isinstance(value, GraphProto):
            a.type, a.g = ATTR_GRAPH, value
        elif isinstance(value, (list, tuple)):
            if value and isinstance(value[0], float):
                a.type, a.floats = ATTR_FLOATS, list(value)
            elif value and isinstance(value[0], str):
                a.type, a.strings = ATTR_STRINGS, [s.encode() for s in value]
            else:
                a.type, a.ints = ATTR_INTS, [int(v) for v in value]
        else:
            raise TypeError(f"unsupported attribute value {value!r}")
        return a


@dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    input: list = field(default_factory=list)
    output: list = field(default_factory=list)
    attribute: list = field(default_factory=list)
    domain: str = ""

    # input=1 output=2 name=3 op_type=4 attribute=5 domain=7
    @classmethod
    def parse(cls, buf):
        n = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                n.input.append(val.decode())
            elif fnum == 2:
                n.output.append(val.decode())
            elif fnum == 3:
                n.name = val.decode()
            elif fnum == 4:
                n.op_type = val.decode()
            elif fnum == 5:
                n.attribute.append(AttributeProto.parse(val))
            elif fnum == 7:
                n.domain = val.decode()
        return n

    def serialize(self) -> bytes:
        out = bytearray()
        for s in self.input:
            _emit(out, 1, 2, s.encode())
        for s in self.output:
            _emit(out, 2, 2, s.encode())
        if self.name:
            _emit(out, 3, 2, self.name.encode())
        _emit(out, 4, 2, self.op_type.encode())
        for a in self.attribute:
            _emit(out, 5, 2, a.serialize())
        return bytes(out)

    def attrs(self) -> dict:
        return {a.name: a.value() for a in self.attribute}


@dataclass
class Dimension:
    dim_value: int = -1
    dim_param: str = ""

    @classmethod
    def parse(cls, buf):
        d = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                d.dim_value = _signed(val)
            elif fnum == 2:
                d.dim_param = val.decode()
        return d

    def serialize(self):
        out = bytearray()
        if self.dim_param:
            _emit(out, 2, 2, self.dim_param.encode())
        else:
            _emit(out, 1, 0, int(self.dim_value))
        return bytes(out)


@dataclass
class ValueInfoProto:
    name: str = ""
    elem_type: int = FLOAT
    shape: list = field(default_factory=list)  # list[int|str]

    # ValueInfoProto: name=1 type=2; TypeProto: tensor_type=1;
    # TypeProto.Tensor: elem_type=1 shape=2; TensorShapeProto: dim=1
    @classmethod
    def parse(cls, buf):
        v = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                v.name = val.decode()
            elif fnum == 2:
                for f2, _, val2 in _fields(val):           # TypeProto
                    if f2 == 1:                             # tensor_type
                        for f3, _, val3 in _fields(val2):
                            if f3 == 1:
                                v.elem_type = val3
                            elif f3 == 2:                   # shape
                                for f4, _, val4 in _fields(val3):
                                    if f4 == 1:
                                        d = Dimension.parse(val4)
                                        v.shape.append(
                                            d.dim_param or d.dim_value)
        return v

    def serialize(self) -> bytes:
        shape_buf = bytearray()
        for d in self.shape:
            dim = Dimension(dim_param=d) if isinstance(d, str) else \
                Dimension(dim_value=int(d))
            _emit(shape_buf, 1, 2, dim.serialize())
        tensor_buf = bytearray()
        _emit(tensor_buf, 1, 0, self.elem_type)
        _emit(tensor_buf, 2, 2, bytes(shape_buf))
        type_buf = bytearray()
        _emit(type_buf, 1, 2, bytes(tensor_buf))
        out = bytearray()
        _emit(out, 1, 2, self.name.encode())
        _emit(out, 2, 2, bytes(type_buf))
        return bytes(out)


@dataclass
class GraphProto:
    name: str = ""
    node: list = field(default_factory=list)
    initializer: list = field(default_factory=list)
    input: list = field(default_factory=list)
    output: list = field(default_factory=list)
    value_info: list = field(default_factory=list)

    # node=1 name=2 initializer=5 input=11 output=12 value_info=13
    @classmethod
    def parse(cls, buf):
        g = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                g.node.append(NodeProto.parse(val))
            elif fnum == 2:
                g.name = val.decode()
            elif fnum == 5:
                g.initializer.append(TensorProto.parse(val))
            elif fnum == 11:
                g.input.append(ValueInfoProto.parse(val))
            elif fnum == 12:
                g.output.append(ValueInfoProto.parse(val))
            elif fnum == 13:
                g.value_info.append(ValueInfoProto.parse(val))
        return g

    def serialize(self) -> bytes:
        out = bytearray()
        for n in self.node:
            _emit(out, 1, 2, n.serialize())
        _emit(out, 2, 2, self.name.encode())
        for t in self.initializer:
            _emit(out, 5, 2, t.serialize())
        for v in self.input:
            _emit(out, 11, 2, v.serialize())
        for v in self.output:
            _emit(out, 12, 2, v.serialize())
        return bytes(out)


@dataclass
class OperatorSetIdProto:
    domain: str = ""
    version: int = 17

    @classmethod
    def parse(cls, buf):
        o = cls()
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                o.domain = val.decode()
            elif fnum == 2:
                o.version = _signed(val)
        return o

    def serialize(self):
        out = bytearray()
        if self.domain:
            _emit(out, 1, 2, self.domain.encode())
        _emit(out, 2, 0, self.version)
        return bytes(out)


@dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = "singa_tpu"
    producer_version: str = "0.1.0"
    graph: "GraphProto | None" = None
    opset_import: list = field(default_factory=lambda: [OperatorSetIdProto()])

    # ir_version=1 producer_name=2 producer_version=3 model_version=5
    # graph=7 opset_import=8
    @classmethod
    def parse(cls, buf):
        m = cls(opset_import=[])
        for fnum, wtype, val in _fields(buf):
            if fnum == 1:
                m.ir_version = _signed(val)
            elif fnum == 2:
                m.producer_name = val.decode()
            elif fnum == 3:
                m.producer_version = val.decode()
            elif fnum == 7:
                m.graph = GraphProto.parse(val)
            elif fnum == 8:
                m.opset_import.append(OperatorSetIdProto.parse(val))
        return m

    def serialize(self) -> bytes:
        out = bytearray()
        _emit(out, 1, 0, self.ir_version)
        _emit(out, 2, 2, self.producer_name.encode())
        _emit(out, 3, 2, self.producer_version.encode())
        _emit(out, 7, 2, self.graph.serialize())
        for o in self.opset_import:
            _emit(out, 8, 2, o.serialize())
        return bytes(out)


def load_model(path_or_bytes) -> ModelProto:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return ModelProto.parse(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return ModelProto.parse(f.read())


def save_model(model: ModelProto, path: str):
    with open(path, "wb") as f:
        f.write(model.serialize())
