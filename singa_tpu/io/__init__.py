"""IO — the rebuild of src/io (snapshot key-value store, binfile
readers/writers, data loaders); native C++ fast path in native/.

``binfile.CorruptRecordError`` (re-exported here) is the typed
corruption surface: a truncated tail record or CRC mismatch names the
key/offset/expected-vs-actual so the resilience layer's checkpoint
fallback can log something actionable."""

from .binfile import CorruptRecordError  # noqa: F401
