"""IO — the rebuild of src/io (snapshot key-value store, binfile
readers/writers, data loaders); native C++ fast path in native/."""
