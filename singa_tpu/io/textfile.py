"""Text-file record IO (reference: src/io/textfile_reader.cc /
textfile_writer.cc, unverified — SURVEY.md §2.1 IO row: line-per-record
text store whose read key is the line number).

Same access API shape as ``binfile`` (count/key/value/items) plus the
reference's Open/Read/Close verbs, so scripts written against either
store port across.  Values are str; newlines inside a value are escaped
so one record is always one physical line (the reference forbids
embedded newlines instead — escaping is strictly more permissive).
"""

from __future__ import annotations

import os


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class TextFileWriter:
    def __init__(self, path, append=False):
        self.path = path
        self._f = open(path, "a" if append else "w", encoding="utf-8")
        self._n = 0

    def put(self, value: str):
        self._f.write(_escape(value) + "\n")
        self._n += 1

    # reference verb aliases
    def Write(self, key, value=None):
        """Reference signature Write(key, value); the key (line number)
        is implicit in a text store, so a single-arg call writes value."""
        self.put(value if value is not None else key)

    def Flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    Close = close

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class TextFileReader:
    def __init__(self, path):
        self.path = path
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        self._lines = raw.split("\n")
        if self._lines and self._lines[-1] == "":
            self._lines.pop()
        self._cursor = 0

    def count(self) -> int:
        return len(self._lines)

    def key(self, i: int) -> str:
        return str(i)

    def value(self, i: int) -> str:
        return _unescape(self._lines[i])

    def items(self):
        for i in range(self.count()):
            yield self.key(i), self.value(i)

    def Read(self):
        """Reference-style sequential read: (key, value) or None at EOF."""
        if self._cursor >= len(self._lines):
            return None
        kv = (str(self._cursor), _unescape(self._lines[self._cursor]))
        self._cursor += 1
        return kv

    def SeekToFirst(self):
        self._cursor = 0

    def close(self):
        self._lines = []

    Close = close

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
