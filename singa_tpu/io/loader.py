"""Threaded data loader over the native prefetch queue (reference parity:
the reference decodes records on loader threads feeding a safe_queue;
here loader threads stage numpy batches while the device runs the
compiled step — host IO hides behind TPU compute)."""

from __future__ import annotations

import struct
import threading

import numpy as np

from .binfile import BinFileReader, PrefetchQueue


def encode_example(x: np.ndarray, y: int) -> bytes:
    hdr = struct.pack("<Iq", x.nbytes, int(y))
    shape = np.asarray(x.shape, np.int32)
    return hdr + struct.pack("<I", len(shape)) + shape.tobytes() + \
        np.ascontiguousarray(x.astype(np.float32)).tobytes()


def decode_example(blob: bytes):
    nbytes, y = struct.unpack("<Iq", blob[:12])
    (ndim,) = struct.unpack("<I", blob[12:16])
    shape = np.frombuffer(blob[16:16 + 4 * ndim], np.int32)
    x = np.frombuffer(blob[16 + 4 * ndim:], np.float32).reshape(shape)
    return x, y


class DataLoader:
    """Iterates (x_batch, y_batch) numpy pairs from a BinFile dataset,
    with ``num_workers`` reader threads prefetching ahead."""

    def __init__(self, path, batch_size, shuffle=True, num_workers=2,
                 seed=0, queue_depth=8):
        self.path = path
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = max(1, num_workers)
        self.seed = seed
        self.queue_depth = queue_depth
        with BinFileReader(path) as r:
            self.n = r.count()

    def __len__(self):
        return self.n // self.batch_size

    def __iter__(self):
        order = np.arange(self.n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed)
            rng.shuffle(order)
            self.seed += 1
        n_batches = len(self)
        q = PrefetchQueue(capacity=self.queue_depth,
                          max_value_bytes=1 << 26)
        batches = [order[i * self.batch_size:(i + 1) * self.batch_size]
                   for i in range(n_batches)]
        todo = list(enumerate(batches))
        lock = threading.Lock()

        def worker():
            reader = BinFileReader(self.path)
            try:
                while True:
                    with lock:
                        if not todo:
                            return
                        bi, idxs = todo.pop(0)
                    xs, ys = [], []
                    for i in idxs:
                        x, y = decode_example(reader.value(int(i)))
                        xs.append(x)
                        ys.append(y)
                    xb = np.stack(xs)
                    yb = np.asarray(ys, np.int32)
                    blob = struct.pack("<I", xb.nbytes) + \
                        struct.pack("<I", xb.ndim) + \
                        np.asarray(xb.shape, np.int32).tobytes() + \
                        xb.tobytes() + yb.tobytes()
                    try:
                        q.put(str(bi), blob)
                    except RuntimeError:
                        return  # queue closed (consumer stopped early)
            finally:
                reader.close()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()

        delivered = 0
        try:
            while delivered < n_batches:
                item = q.get()
                if item is None:
                    break
                _, blob = item
                (xb_nbytes,) = struct.unpack("<I", blob[:4])
                (ndim,) = struct.unpack("<I", blob[4:8])
                shape = np.frombuffer(blob[8:8 + 4 * ndim], np.int32)
                off = 8 + 4 * ndim
                xb = np.frombuffer(blob[off:off + xb_nbytes],
                                   np.float32).reshape(shape)
                yb = np.frombuffer(blob[off + xb_nbytes:], np.int32)
                delivered += 1
                yield xb, yb
        finally:
            q.close()
            for t in threads:
                t.join(timeout=5)
            q.free()


def write_dataset(path, xs: np.ndarray, ys: np.ndarray):
    """Create a BinFile dataset from arrays."""
    from .binfile import BinFileWriter

    with BinFileWriter(path) as w:
        for i in range(len(xs)):
            w.put(f"rec_{i:08d}", encode_example(xs[i], int(ys[i])))
