"""JPEG image codec (reference: src/io/jpg_encoder.cc / jpg_decoder.cc
over libjpeg via opencv, unverified — SURVEY.md §2.1 IO row).

PIL-backed: encode an HWC uint8 numpy array to JPEG bytes and back.
PIL ships with this environment; if it is ever absent the codec raises a
clear ImportError at first use (the rest of singa_tpu.io has no image
dependency — BinFile/Text stores carry raw arrays fine without it).
"""

from __future__ import annotations

import io as _io

import numpy as np


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "singa_tpu.io.image needs Pillow for JPEG encode/decode; "
            "store raw arrays via io.loader/binfile instead") from e
    return Image


class JPGEncoder:
    """numpy HWC uint8 (or HW grayscale) -> JPEG bytes."""

    def __init__(self, quality=95):
        self.quality = int(quality)

    def encode(self, arr: np.ndarray) -> bytes:
        Image = _pil()
        arr = np.ascontiguousarray(arr)
        if arr.dtype != np.uint8:
            raise ValueError(f"JPEG encode expects uint8, got {arr.dtype}")
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=self.quality)
        return buf.getvalue()

    Encode = encode


class JPGDecoder:
    """JPEG bytes -> numpy HWC uint8 (RGB) or HW (grayscale)."""

    def decode(self, blob: bytes) -> np.ndarray:
        Image = _pil()
        return np.asarray(Image.open(_io.BytesIO(blob)))

    Decode = decode


def encode_jpg(arr, quality=95) -> bytes:
    return JPGEncoder(quality).encode(arr)


def decode_jpg(blob) -> np.ndarray:
    return JPGDecoder().decode(blob)
