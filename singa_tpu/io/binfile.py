"""BinFile record store + prefetch queue — ctypes bindings over the
native C++ runtime in ``native/singa_io.cpp`` (reference parity:
src/io/ BinFileReader/Writer + utils/safe_queue, unverified).

The native library is built on first use (``make -C native``); if no
toolchain is available a pure-Python fallback provides the same API so
the framework stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libsinga_io.so")

_lib = None
_lib_err = None
_build_lock = threading.Lock()


def _load_native():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            src = os.path.join(_NATIVE_DIR, "singa_io.cpp")
            stale = (not os.path.exists(_SO_PATH)
                     or (os.path.exists(src)
                         and os.path.getmtime(src) > os.path.getmtime(
                             _SO_PATH)))
            if stale:
                subprocess.run(["make", "-B", "-C", _NATIVE_DIR],
                               check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO_PATH)
            lib.binfile_writer_open.restype = ctypes.c_void_p
            lib.binfile_writer_open.argtypes = [ctypes.c_char_p]
            lib.binfile_writer_put.restype = ctypes.c_int
            lib.binfile_writer_put.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64]
            lib.binfile_writer_close.restype = ctypes.c_int
            lib.binfile_writer_close.argtypes = [ctypes.c_void_p]
            lib.binfile_reader_open.restype = ctypes.c_void_p
            lib.binfile_reader_open.argtypes = [ctypes.c_char_p]
            lib.binfile_reader_count.restype = ctypes.c_int64
            lib.binfile_reader_count.argtypes = [ctypes.c_void_p]
            lib.binfile_reader_key.restype = ctypes.c_int64
            lib.binfile_reader_key.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int64]
            lib.binfile_reader_val_len.restype = ctypes.c_int64
            lib.binfile_reader_val_len.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int64]
            lib.binfile_reader_val.restype = ctypes.c_int64
            lib.binfile_reader_val.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int64]
            lib.binfile_reader_close.restype = ctypes.c_int
            lib.binfile_reader_close.argtypes = [ctypes.c_void_p]
            for name, res, args in [
                ("prefetch_queue_new", ctypes.c_void_p, [ctypes.c_int64]),
                ("prefetch_queue_put", ctypes.c_int,
                 [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                  ctypes.c_uint64]),
                ("prefetch_queue_get", ctypes.c_int64,
                 [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                  ctypes.c_char_p, ctypes.c_int64]),
                ("prefetch_queue_size", ctypes.c_int64, [ctypes.c_void_p]),
                ("prefetch_queue_close", None, [ctypes.c_void_p]),
                ("prefetch_queue_free", None, [ctypes.c_void_p]),
                ("augment_batch", ctypes.c_int,
                 [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                  ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                  ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                  ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
                  ctypes.c_void_p]),
            ]:
                fn = getattr(lib, name)
                fn.restype = res
                fn.argtypes = args
            _lib = lib
        except Exception as e:  # toolchain missing etc.
            _lib_err = e
        return _lib


def native_available() -> bool:
    return _load_native() is not None


_MAGIC = b"NSTGAIO1"


class CorruptRecordError(OSError):
    """A BinFile record failed integrity checks: a truncated tail
    (crash mid-write) or a CRC mismatch.  Carries enough to log
    something actionable — ``key`` (None when the truncation ate the
    key itself), byte ``offset`` of the bad record, and for CRC
    failures the ``expected`` vs ``actual`` checksum.  Classified
    FATAL by the retry layer (corruption never heals on retry); the
    CheckpointManager fallback walk absorbs it instead."""

    def __init__(self, path, reason, key=None, offset=None,
                 expected=None, actual=None):
        detail = f"{path}: {reason}"
        if key is not None:
            detail += f" (key={key!r}"
            if expected is not None:
                detail += (f", crc expected=0x{expected:08x} "
                           f"actual=0x{actual:08x}")
            if offset is not None:
                detail += f", offset={offset}"
            detail += ")"
        elif offset is not None:
            detail += f" (offset={offset})"
        super().__init__(detail)
        self.path = path
        self.reason = reason
        self.key = key
        self.offset = offset
        self.expected = expected
        self.actual = actual


def _fault_check():
    """io.binfile injection site — one module-flag read when disarmed."""
    from ..resilience import faults

    if faults._armed:
        faults.check("io.binfile")


class BinFileWriter:
    """Append key->bytes records (reference: io::BinFileWriter)."""

    def __init__(self, path):
        self.path = path
        self._lib = _load_native()
        if self._lib is not None:
            self._h = self._lib.binfile_writer_open(path.encode())
            if not self._h:
                raise OSError(f"cannot open {path}")
            self._f = None
        else:
            self._f = open(path, "wb")
            self._f.write(_MAGIC)
            self._h = None

    def put(self, key: str, value: bytes):
        _fault_check()
        if self._h is not None:
            rc = self._lib.binfile_writer_put(self._h, key.encode(), value,
                                              len(value))
            if rc != 0:
                raise OSError(f"write failed for key {key}")
        else:
            k = key.encode()
            self._f.write(struct.pack("<I", len(k)))
            self._f.write(k)
            self._f.write(struct.pack("<Q", len(value)))
            self._f.write(value)
            self._f.write(struct.pack("<I", zlib.crc32(value) & 0xFFFFFFFF))

    def close(self):
        if self._h is not None:
            self._lib.binfile_writer_close(self._h)
            self._h = None
        elif self._f:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class BinFileReader:
    """Read records; random access by index or key."""

    def __init__(self, path):
        self.path = path
        _fault_check()
        self._lib = _load_native()
        if self._lib is not None:
            self._h = self._lib.binfile_reader_open(path.encode())
            if not self._h:
                raise OSError(f"cannot open/parse {path}")
            self._keys = None
        else:
            self._h = None
            self._records = []
            fsize = os.path.getsize(path)
            with open(path, "rb") as f:
                if f.read(8) != _MAGIC:
                    raise OSError(f"bad magic in {path}")
                while True:
                    rec_off = f.tell()
                    hdr = f.read(4)
                    if len(hdr) == 0:
                        break  # clean EOF on a record boundary
                    if len(hdr) < 4:
                        raise CorruptRecordError(
                            path, "truncated tail: partial key-length "
                            "header (crash mid-write?)", offset=rec_off)
                    (klen,) = struct.unpack("<I", hdr)
                    # bound lengths against the file BEFORE reading: a
                    # bit-flipped length field must surface as typed
                    # corruption, not a multi-GB read/MemoryError
                    if klen > fsize - f.tell():
                        raise CorruptRecordError(
                            path, f"key length {klen} exceeds "
                            f"remaining file (corrupt header?)",
                            offset=rec_off)
                    kraw = f.read(klen)
                    if len(kraw) < klen:
                        raise CorruptRecordError(
                            path, "truncated tail: key cut short",
                            offset=rec_off)
                    key = kraw.decode()
                    vhdr = f.read(8)
                    if len(vhdr) < 8:
                        raise CorruptRecordError(
                            path, "truncated tail: partial value-length "
                            "header", key=key, offset=rec_off)
                    (vlen,) = struct.unpack("<Q", vhdr)
                    if vlen > fsize - f.tell():
                        raise CorruptRecordError(
                            path, f"value length {vlen} exceeds "
                            f"remaining file (corrupt header or "
                            f"truncated tail)", key=key, offset=rec_off)
                    val = f.read(vlen)
                    if len(val) < vlen:
                        raise CorruptRecordError(
                            path, f"truncated tail: value cut at "
                            f"{len(val)}/{vlen} bytes", key=key,
                            offset=rec_off)
                    craw = f.read(4)
                    if len(craw) < 4:
                        raise CorruptRecordError(
                            path, "truncated tail: CRC footer missing",
                            key=key, offset=rec_off)
                    (crc,) = struct.unpack("<I", craw)
                    actual = zlib.crc32(val) & 0xFFFFFFFF
                    if actual != crc:
                        raise CorruptRecordError(
                            path, "CRC mismatch", key=key,
                            offset=rec_off, expected=crc, actual=actual)
                    self._records.append((key, val))

    def count(self) -> int:
        if self._h is not None:
            return int(self._lib.binfile_reader_count(self._h))
        return len(self._records)

    def key(self, i: int) -> str:
        if self._h is not None:
            buf = ctypes.create_string_buffer(4096)
            n = self._lib.binfile_reader_key(self._h, i, buf, 4096)
            if n < 0:
                raise IndexError(i)
            return buf.value.decode()
        return self._records[i][0]

    def value(self, i: int) -> bytes:
        if self._h is not None:
            n = self._lib.binfile_reader_val_len(self._h, i)
            if n < 0:
                raise IndexError(i)
            buf = ctypes.create_string_buffer(int(n) if n else 1)
            rc = self._lib.binfile_reader_val(self._h, i, buf, n)
            if rc == -2:
                raise CorruptRecordError(self.path, "CRC mismatch",
                                         key=self.key(i))
            if rc < 0:
                raise OSError(f"read failed at record {i}")
            return buf.raw[:n]
        return self._records[i][1]

    def items(self):
        for i in range(self.count()):
            yield self.key(i), self.value(i)

    def read_all(self) -> dict:
        return dict(self.items())

    def close(self):
        if self._h is not None:
            self._lib.binfile_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class PrefetchQueue:
    """Blocking MPMC queue backed by the native ring buffer; Python
    fallback uses queue.Queue."""

    def __init__(self, capacity=64, max_value_bytes=1 << 24):
        self._lib = _load_native()
        self.max_value_bytes = max_value_bytes
        if self._lib is not None:
            self._h = self._lib.prefetch_queue_new(capacity)
        else:
            import queue

            self._h = None
            self._q = queue.Queue(maxsize=capacity)

    def put(self, key: str, value: bytes):
        if self._h is not None:
            rc = self._lib.prefetch_queue_put(self._h, key.encode(), value,
                                              len(value))
            if rc != 0:
                raise RuntimeError("queue closed")
        else:
            self._q.put((key, value))

    def get(self):
        """Returns (key, value) or None when closed and drained."""
        if self._h is not None:
            kbuf = ctypes.create_string_buffer(4096)
            vbuf = ctypes.create_string_buffer(self.max_value_bytes)
            n = self._lib.prefetch_queue_get(self._h, kbuf, 4096, vbuf,
                                             self.max_value_bytes)
            if n == -1:
                return None
            if n < 0:
                raise RuntimeError("record larger than max_value_bytes")
            return kbuf.value.decode(), vbuf.raw[:n]
        item = self._q.get()
        return item  # None sentinel signals closed

    def qsize(self):
        if self._h is not None:
            return int(self._lib.prefetch_queue_size(self._h))
        return self._q.qsize()

    def close(self):
        if self._h is not None:
            self._lib.prefetch_queue_close(self._h)
        else:
            self._q.put(None)

    def free(self):
        if self._h is not None:
            self._lib.prefetch_queue_free(self._h)
            self._h = None
