"""Image augmentation helpers (reference: python/singa/image_tool.py,
unverified — resize/crop/flip pipelines used by the CNN examples).

numpy-only implementation (no PIL dependency guaranteed in this image);
images are HWC uint8/float arrays or NCHW float batches.
"""

from __future__ import annotations

import numpy as np


def crop(img, patch, position="center"):
    """img HWC; patch (h, w); position in {'center','left_top', 'left_bottom',
    'right_top','right_bottom','random'}."""
    h, w = img.shape[:2]
    ph, pw = patch
    assert ph <= h and pw <= w, f"patch {patch} larger than image {(h, w)}"
    if position == "center":
        y, x = (h - ph) // 2, (w - pw) // 2
    elif position == "left_top":
        y, x = 0, 0
    elif position == "left_bottom":
        y, x = h - ph, 0
    elif position == "right_top":
        y, x = 0, w - pw
    elif position == "right_bottom":
        y, x = h - ph, w - pw
    elif position == "random":
        y = np.random.randint(0, h - ph + 1)
        x = np.random.randint(0, w - pw + 1)
    else:
        raise ValueError(position)
    return img[y:y + ph, x:x + pw]


def flip(img, direction="horizontal"):
    if direction == "horizontal":
        return img[:, ::-1]
    if direction == "vertical":
        return img[::-1]
    raise ValueError(direction)


def resize(img, size):
    """Bilinear resize, HWC -> (size_h, size_w, C)."""
    if isinstance(size, int):
        size = (size, size)
    h, w = img.shape[:2]
    th, tw = size
    ys = np.linspace(0, h - 1, th)
    xs = np.linspace(0, w - 1, tw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None] if img.ndim == 3 else (ys - y0)[:, None]
    wx = (xs - x0)[None, :, None] if img.ndim == 3 else (xs - x0)[None, :]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def color_jitter(img, brightness=0.0, contrast=0.0, rng=None):
    rng = rng or np.random
    out = img.astype(np.float32)
    if brightness:
        out = out + rng.uniform(-brightness, brightness) * 255.0
    if contrast:
        mean = out.mean()
        out = (out - mean) * (1 + rng.uniform(-contrast, contrast)) + mean
    return np.clip(out, 0, 255)


def normalize(img, mean, std):
    """HWC or NCHW; mean/std per channel."""
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    img = img.astype(np.float32)
    if img.ndim == 4:  # NCHW
        return (img - mean[None, :, None, None]) / std[None, :, None, None]
    return (img - mean) / std


def to_chw(img):
    return np.transpose(img, (2, 0, 1))


class ImageTool:
    """Chainable augmentation pipeline (reference ImageTool API shape):
    ImageTool(img).resize(40).crop((32,32),'random').flip().get()"""

    def __init__(self, img):
        self.img = np.asarray(img)

    def resize_by_range(self, rng_size):
        size = np.random.randint(rng_size[0], rng_size[1] + 1)
        self.img = resize(self.img, size)
        return self

    def resize(self, size):
        self.img = resize(self.img, size)
        return self

    def crop(self, patch, position="center"):
        self.img = crop(self.img, patch, position)
        return self

    def flip(self, direction="horizontal", prob=1.0):
        if np.random.rand() < prob:
            self.img = flip(self.img, direction)
        return self

    def get(self):
        return self.img
