"""Image augmentation helpers (reference: python/singa/image_tool.py,
unverified — resize/crop/flip pipelines used by the CNN examples).

numpy-only implementation (no PIL dependency guaranteed in this image);
images are HWC uint8/float arrays or NCHW float batches.
"""

from __future__ import annotations

import numpy as np


def crop(img, patch, position="center"):
    """img HWC; patch (h, w); position in {'center','left_top', 'left_bottom',
    'right_top','right_bottom','random'}."""
    h, w = img.shape[:2]
    ph, pw = patch
    assert ph <= h and pw <= w, f"patch {patch} larger than image {(h, w)}"
    if position == "center":
        y, x = (h - ph) // 2, (w - pw) // 2
    elif position == "left_top":
        y, x = 0, 0
    elif position == "left_bottom":
        y, x = h - ph, 0
    elif position == "right_top":
        y, x = 0, w - pw
    elif position == "right_bottom":
        y, x = h - ph, w - pw
    elif position == "random":
        y = np.random.randint(0, h - ph + 1)
        x = np.random.randint(0, w - pw + 1)
    else:
        raise ValueError(position)
    return img[y:y + ph, x:x + pw]


def flip(img, direction="horizontal"):
    if direction == "horizontal":
        return img[:, ::-1]
    if direction == "vertical":
        return img[::-1]
    raise ValueError(direction)


def resize(img, size):
    """Bilinear resize, HWC -> (size_h, size_w, C)."""
    if isinstance(size, int):
        size = (size, size)
    h, w = img.shape[:2]
    th, tw = size
    ys = np.linspace(0, h - 1, th)
    xs = np.linspace(0, w - 1, tw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None] if img.ndim == 3 else (ys - y0)[:, None]
    wx = (xs - x0)[None, :, None] if img.ndim == 3 else (xs - x0)[None, :]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def color_jitter(img, brightness=0.0, contrast=0.0, rng=None):
    rng = rng or np.random
    out = img.astype(np.float32)
    if brightness:
        out = out + rng.uniform(-brightness, brightness) * 255.0
    if contrast:
        mean = out.mean()
        out = (out - mean) * (1 + rng.uniform(-contrast, contrast)) + mean
    return np.clip(out, 0, 255)


def normalize(img, mean, std):
    """HWC or NCHW; mean/std per channel."""
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    img = img.astype(np.float32)
    if img.ndim == 4:  # NCHW
        return (img - mean[None, :, None, None]) / std[None, :, None, None]
    return (img - mean) / std


def to_chw(img):
    return np.transpose(img, (2, 0, 1))


def augment_batch(imgs, patch, mean=None, std=None, train=True, seed=0,
                  threads=0):
    """Fused batch augmentation: per-image random crop to ``patch`` +
    coin-flip horizontal mirror (train) or center crop (eval), uint8
    NHWC -> normalized float32 NCHW.

    Runs in the native C++ runtime when available (native/singa_io.cpp
    ``augment_batch`` — one threaded pass per image, the reference's
    C++ transformer equivalent); falls back to numpy with identical
    EVAL-mode output (train-mode random draws differ between the two
    implementations — both are deterministic in ``seed``).
    """
    import ctypes

    from .io import binfile as _bf

    imgs = np.ascontiguousarray(imgs, np.uint8)
    assert imgs.ndim == 4, "imgs must be (N, H, W, C) uint8"
    n, h, w, c = imgs.shape
    ph, pw = (patch, patch) if isinstance(patch, int) else patch
    assert ph <= h and pw <= w, f"patch {patch} larger than {(h, w)}"
    # broadcast to per-channel length — the native loop indexes [ch]
    mean_a = np.ascontiguousarray(np.broadcast_to(
        np.asarray(0.0 if mean is None else mean, np.float32), (c,)))
    std_a = np.ascontiguousarray(np.broadcast_to(
        np.asarray(1.0 if std is None else std, np.float32), (c,)))
    out = np.empty((n, c, ph, pw), np.float32)

    lib = _bf._load_native()
    if lib is not None and hasattr(lib, "augment_batch"):
        rc = lib.augment_batch(
            imgs.ctypes.data_as(ctypes.c_void_p), n, h, w, c, ph, pw,
            mean_a.ctypes.data_as(ctypes.c_void_p),
            std_a.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(seed), 1 if train else 0, threads,
            out.ctypes.data_as(ctypes.c_void_p))
        if rc == 0:
            return out
    # numpy fallback
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    fimgs = imgs.astype(np.float32) / 255.0
    for i in range(n):
        if train:
            y = rng.randint(0, h - ph + 1)
            x = rng.randint(0, w - pw + 1)
            mirror = rng.rand() < 0.5
        else:
            y, x = (h - ph) // 2, (w - pw) // 2
            mirror = False
        im = fimgs[i, y:y + ph, x:x + pw]
        if mirror:
            im = im[:, ::-1]
        out[i] = np.transpose((im - mean_a) / std_a, (2, 0, 1))
    return out


class ImageTool:
    """Chainable augmentation pipeline (reference ImageTool API shape):
    ImageTool(img).resize(40).crop((32,32),'random').flip().get()"""

    def __init__(self, img):
        self.img = np.asarray(img)

    def resize_by_range(self, rng_size):
        size = np.random.randint(rng_size[0], rng_size[1] + 1)
        self.img = resize(self.img, size)
        return self

    def resize(self, size):
        self.img = resize(self.img, size)
        return self

    def crop(self, patch, position="center"):
        self.img = crop(self.img, patch, position)
        return self

    def flip(self, direction="horizontal", prob=1.0):
        if np.random.rand() < prob:
            self.img = flip(self.img, direction)
        return self

    def get(self):
        return self.img
