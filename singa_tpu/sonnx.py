"""ONNX front end (reference: ``python/singa/sonnx.py``, ~2.3k LoC,
unverified — SURVEY.md §2.2/§3.4): ``SingaBackend`` (``prepare()`` →
op-dispatch dict onnx-op → singa op), ``SingaRep.run``, ``SingaFrontend``
(``to_onnx`` export), ``SONNXModel`` training wrapper.

TPU-native notes: the reference depends on the ``onnx`` pip package; here
the protobuf layer is the vendored codec in ``io/onnx_pb.py`` (no
network, no wheel — SURVEY.md §7 step 7).  Imported graphs execute as
ordinary singa_tpu autograd ops, so a prepared model can be wrapped in
``SONNXModel`` and *trained* under graph mode like any native model
(config #4: BERT-base import path).
"""

from __future__ import annotations

import contextvars
import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd, layer, model, tensor
from .device import get_default_device
from .io import onnx_pb
from .io.onnx_pb import (AttributeProto, GraphProto, ModelProto, NodeProto,
                         TensorProto, ValueInfoProto)
from .tensor import Tensor
from .autograd import _op

# ---------------------------------------------------------------------------
# Backend: ONNX -> singa ops
# ---------------------------------------------------------------------------


def _np(t):
    return tensor.to_numpy(t) if isinstance(t, Tensor) else np.asarray(t)


# device of the SingaRep currently executing run() — consulted by handlers
# that materialize new tensors (Constant/Shape/Range/...), so imported
# graphs run wholly on the rep's device (ADVICE r01: from_numpy without a
# device committed constants to the default CPU device and broke jitted
# TPU execution).  A ContextVar so concurrent run()s on different
# threads each see their own rep's device.
_REP_DEVICE = contextvars.ContextVar("sonnx_rep_device", default=None)


def _rep_device():
    d = _REP_DEVICE.get()
    return d if d is not None else get_default_device()


class SingaRep:
    """Executable representation of an imported graph (reference:
    SingaRep).  ``run(inputs)`` walks the nodes in graph order; tensors
    flow through singa autograd ops, so when ``autograd.training`` is on
    the whole imported graph is differentiable."""

    def __init__(self, graph: GraphProto, weights: dict, device,
                 outputs=None):
        self.graph = graph
        self.device = device
        self.weights = weights  # name -> Tensor (initializers, trainable)
        self.output_names = outputs or [v.name for v in graph.output]
        # Constant nodes evaluate once here, NOT per run(): they are
        # frozen values (baked causal masks, attention scales, shapes) —
        # never trainable, and hoisting them avoids a host->device
        # transfer every forward
        self._consts = {}
        token = _REP_DEVICE.set(device)
        try:
            for node in graph.node:
                if node.op_type == "Constant" and node.output:
                    t = _ONNX_OPS["Constant"](node, [])
                    t.requires_grad = False
                    t.stores_grad = False
                    self._consts[node.output[0]] = t
        finally:
            _REP_DEVICE.reset(token)
        # BatchNormalization mean/var inputs are MUTABLE training state
        # (the training branch writes running stats into them), not
        # frozen constants: promote them to non-trainable weights so
        # get_states()/persistent_tensors() track them and graph mode
        # threads them through the compiled step instead of leaking
        # traced values into untracked tensors
        for node in graph.node:
            if node.op_type == "BatchNormalization":
                for name in list(node.input)[3:5]:
                    if name in self._consts:
                        t = self._consts.pop(name)
                        t.name = name
                        self.weights[name] = t

    def params(self):
        return self.weights

    def run(self, inputs):
        env = dict(self.weights)
        env.update(self._consts)
        graph_inputs = [v.name for v in self.graph.input
                        if v.name not in self.weights]
        if isinstance(inputs, dict):
            for k, v in inputs.items():
                env[k] = v if isinstance(v, Tensor) else \
                    tensor.from_numpy(np.asarray(v), self.device)
        else:
            if len(inputs) != len(graph_inputs):
                raise ValueError(
                    f"expected {len(graph_inputs)} inputs "
                    f"({graph_inputs}), got {len(inputs)}")
            for k, v in zip(graph_inputs, inputs):
                env[k] = v if isinstance(v, Tensor) else \
                    tensor.from_numpy(np.asarray(v), self.device)
        # constants created by handlers land on the rep's device, not
        # the default
        token = _REP_DEVICE.set(self.device)
        try:
            # skip hoisted constants AND promoted BN stats: a Constant
            # node whose output was promoted into weights must not
            # re-execute, or its frozen export-time value would shadow
            # the live (trained/loaded) running stats in env
            _exec_nodes(self.graph.node, env,
                        skip_consts=set(self._consts) | set(self.weights))
        finally:
            _REP_DEVICE.reset(token)
        return [env[n] for n in self.output_names]


def _exec_nodes(nodes, env, skip_consts=()):
    """Walk nodes in graph order, updating ``env`` (name -> Tensor).
    Shared by SingaRep.run and the If/Loop subgraph handlers — ONNX
    subgraphs capture outer-scope names, so control-flow ops execute
    their bodies against a CHILD copy of the enclosing env (ONNX spec:
    outer names visible, inner bindings don't leak)."""
    for node in nodes:
        if node.op_type == "Constant" and node.output \
                and node.output[0] in skip_consts:
            continue  # pre-evaluated at prepare time
        if node.op_type in ("If", "Loop", "Scan"):
            outs = _exec_control_flow(node, env)
        else:
            handler = _ONNX_OPS.get(node.op_type)
            if handler is None:
                raise NotImplementedError(
                    f"ONNX op {node.op_type!r} is not supported by sonnx")
            args = [env[i] if i else None for i in node.input]
            outs = handler(node, args)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for name, out in zip(node.output, outs):
            if name:
                env[name] = out


def _run_subgraph(graph, env, bound_inputs):
    """Execute a subgraph against a child env; returns its outputs in
    declaration order.  ``bound_inputs``: Tensors for the subgraph's
    formal inputs (ONNX: subgraph inputs shadow outer names)."""
    child = dict(env)
    # ONNX scoping: names DEFINED by the subgraph (its initializers and
    # formal inputs) shadow identically-named outer values — load
    # initializers unconditionally, then bind formals over them
    for init in graph.initializer:
        child[init.name] = tensor.from_numpy(init.to_numpy(),
                                             _rep_device())
    for vi, t in zip(graph.input, bound_inputs):
        child[vi.name] = t
    _exec_nodes(graph.node, child)
    return [child[v.name] for v in graph.output]


def _concrete_bool(t):
    """Python bool of a 0-d condition tensor, or None while tracing
    (jax tracers have no concrete value)."""
    try:
        return bool(np.asarray(t.data).reshape(()))
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        return None


def _exec_control_flow(node, env):
    """ONNX If / Loop (SURVEY.md §3.4 — beyond upstream parity, whose
    sonnx is a flat node dispatch with no subgraph support).

    If: a concrete condition Python-branches (eager, or compile-time
    constant under jit); a TRACED condition lowers to ``lax.cond`` with
    both subgraphs traced as pure functions over the captured
    outer-scope tensors (XLA's native conditional — both branches must
    produce matching shapes/dtypes, the ONNX requirement).

    Loop: the common ONNX form — concrete max trip count M, loop-carried
    values, optional early-exit condition, scan outputs stacked along a
    new leading axis.  Runs as a Python loop over the taped ops: exact
    and differentiable in eager; under jit a concrete M unrolls into
    the trace (a traced M or traced exit condition raises — use
    ``lax.scan`` via the native API for that regime)."""
    attrs = node.attrs()
    if node.op_type == "Scan":
        return _exec_scan(node, env)
    if node.op_type == "If":
        cond = env[node.input[0]]
        then_g, else_g = attrs["then_branch"], attrs["else_branch"]
        cb = _concrete_bool(cond)
        if cb is not None:
            return _run_subgraph(then_g if cb else else_g, env, [])
        # traced condition -> lax.cond over pure branch functions.
        # Captured outer names = every input name referenced anywhere in
        # either subgraph (RECURSING into nested If/Loop bodies) that
        # exists in the enclosing env.
        def referenced(g, acc):
            for n in g.node:
                acc.update(i for i in n.input if i)
                for a in n.attribute:
                    if a.g is not None:
                        referenced(a.g, acc)
            return acc

        refs = set()
        referenced(then_g, refs)
        referenced(else_g, refs)
        cap_names = sorted(r for r in refs if r in env)
        cap = [env[n] for n in cap_names]

        def fn(cv, *arrays):
            def branch(g):
                def run(arrs):
                    benv = {n: tensor._wrap(a, _rep_device())
                            for n, a in zip(cap_names, arrs)}
                    outs = _run_subgraph(g, benv, [])
                    return tuple(o.data for o in outs)
                return run
            return jax.lax.cond(jnp.reshape(cv, ()).astype(bool),
                                branch(then_g), branch(else_g),
                                tuple(arrays))

        out = autograd._op(fn, cond, *cap, _name="If")
        return out if isinstance(out, (list, tuple)) else [out]

    # Loop
    body = attrs["body"]
    m_t = env.get(node.input[0]) if node.input[0] else None
    cond_t = env.get(node.input[1]) if len(node.input) > 1 \
        and node.input[1] else None
    carried = [env[i] for i in node.input[2:]]
    n_carried = len(carried)
    n_scan = len(body.output) - 1 - n_carried

    if m_t is None:
        raise NotImplementedError(
            "sonnx Loop requires a max trip count (while-style Loops "
            "with only a dynamic condition are not supported)")
    try:
        m = int(np.asarray(m_t.data).reshape(()))
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        raise NotImplementedError(
            "sonnx Loop requires a CONCRETE max trip count (traced trip "
            "counts need the native lax.scan API)") from None

    dev = _rep_device()
    cond_val = True if cond_t is None else _concrete_bool(cond_t)
    if cond_val is None:
        raise NotImplementedError(
            "sonnx Loop requires a concrete initial condition")
    scans = [[] for _ in range(n_scan)]
    cond_cur = tensor.from_numpy(np.asarray(cond_val), dev) \
        if cond_t is None else cond_t
    for it in range(m):
        cb = _concrete_bool(cond_cur)
        if cb is False:
            break
        if cb is None:
            raise NotImplementedError(
                "sonnx Loop: the exit condition became data-dependent "
                "under tracing; only concrete conditions are supported")
        it_t = tensor.from_numpy(np.asarray(it, np.int64), dev)
        outs = _run_subgraph(body, env, [it_t, cond_cur] + carried)
        cond_cur = outs[0]
        carried = list(outs[1:1 + n_carried])
        for j in range(n_scan):
            scans[j].append(autograd.unsqueeze(outs[1 + n_carried + j], 0))
    if any(not s for s in scans):
        raise NotImplementedError(
            "sonnx Loop: zero-iteration scan outputs (empty tensors) "
            "are not supported")
    stacked = [autograd.cat(s, axis=0) for s in scans]
    return carried + stacked


def _exec_scan(node, env):
    """ONNX Scan (completes the control-flow trio with If/Loop): a
    recurrence with M loop-carried states and N sequence inputs whose
    trip count is the scan axis LENGTH — always static under tracing,
    so the unrolled taped execution is exact, jit-safe, and
    differentiable.  Supports scan_input/output_axes and forward/
    reverse directions."""
    attrs = node.attrs()
    body = attrs["body"]
    n_scan_in = int(attrs["num_scan_inputs"])
    ins = [env[i] for i in node.input]
    n_state = len(ins) - n_scan_in
    states = list(ins[:n_state])
    xs = ins[n_state:]
    in_axes = list(attrs.get("scan_input_axes") or [0] * n_scan_in)
    in_dirs = list(attrs.get("scan_input_directions") or [0] * n_scan_in)
    trip = xs[0].shape[in_axes[0]]
    for x, ax in zip(xs, in_axes):
        if x.shape[ax] != trip:
            raise ValueError(
                f"sonnx Scan: scan inputs disagree on trip count "
                f"({x.shape[ax]} vs {trip})")
    if trip == 0:
        raise NotImplementedError(
            "sonnx Scan: zero-length scan axis (empty scan outputs) is "
            "not supported")
    scans = None
    for t in range(trip):
        slices = []
        for x, ax, dr in zip(xs, in_axes, in_dirs):
            idx = trip - 1 - t if dr else t
            slices.append(autograd._op(
                lambda a, idx, ax: jnp.take(a, idx, axis=ax),
                x, _name="ScanSlice", idx=idx, ax=ax))
        outs = _run_subgraph(body, env, states + slices)
        states = list(outs[:n_state])
        youts = outs[n_state:]
        if scans is None:
            scans = [[] for _ in youts]
        for j, y in enumerate(youts):
            scans[j].append(y)
    scans = scans or []
    k = len(scans)
    out_axes = list(attrs.get("scan_output_axes") or [0] * k)
    out_dirs = list(attrs.get("scan_output_directions") or [0] * k)
    stacked = []
    for ys, ax, dr in zip(scans, out_axes, out_dirs):
        if dr:
            ys = ys[::-1]
        if ax < 0:  # negative axes are relative to the STACKED rank
            ax += len(ys[0].shape) + 1
        ys = [autograd.unsqueeze(y, ax) for y in ys]
        stacked.append(autograd.cat(ys, axis=ax))
    return states + stacked


class SingaBackend:
    @staticmethod
    def prepare(onnx_model, device=None, **kw):
        device = device or get_default_device()
        if isinstance(onnx_model, (str, bytes, bytearray)):
            onnx_model = onnx_pb.load_model(onnx_model)
        g = onnx_model.graph
        weights = {}
        for init in g.initializer:
            arr = init.to_numpy()
            t = tensor.from_numpy(
                arr.astype(np.float32) if arr.dtype == np.float64 else arr,
                device)
            if np.issubdtype(arr.dtype, np.floating):
                t.requires_grad = True
                t.stores_grad = True
            t.name = init.name
            weights[init.name] = t
        return SingaRep(g, weights, device)


prepare = SingaBackend.prepare


class SONNXModel(model.Model):
    """Wrap an imported graph as a trainable Model (reference: SONNXModel).
    Subclass and override train_one_batch, or use as a forward-only
    module."""

    def __init__(self, onnx_model, device=None):
        super().__init__()
        self.rep = SingaBackend.prepare(onnx_model, device)

    def get_params(self):
        return {k: v for k, v in self.rep.weights.items() if v.stores_grad}

    def get_states(self):
        return dict(self.rep.weights)

    def set_states(self, states):
        for k, t in self.rep.weights.items():
            if k in states:
                layer.Layer._load_into(t, states[k])

    def forward(self, *x):
        outs = self.rep.run(list(x))
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# op handlers: each takes (node, args: list[Tensor|None]) -> Tensor(s)
# ---------------------------------------------------------------------------

def _static_ints(t):
    return [int(v) for v in _np(t).reshape(-1)]


def _handle_binary(fn):
    def h(node, args):
        return _op(fn, args[0], args[1], _name=node.op_type)
    return h


def _handle_unary(fn):
    def h(node, args):
        return _op(fn, args[0], _name=node.op_type)
    return h


def _h_gemm(node, args):
    a = node.attrs()
    return autograd.gemm(args[0], args[1],
                         args[2] if len(args) > 2 else None,
                         alpha=a.get("alpha", 1.0), beta=a.get("beta", 1.0),
                         transA=bool(a.get("transA", 0)),
                         transB=bool(a.get("transB", 0)))


def _h_conv(node, args):
    from .ops import conv as conv_ops

    a = node.attrs()
    kernel = a.get("kernel_shape", list(args[1].shape[2:]))
    pads = a.get("pads", [0] * 2 * len(kernel))
    strides = a.get("strides", [1] * len(kernel))
    dil = a.get("dilations", [1] * len(kernel))
    group = a.get("group", 1)
    auto_pad = a.get("auto_pad", "NOTSET")
    n = len(kernel)
    pairs = tuple((pads[i], pads[i + n]) for i in range(n))
    return conv_ops.conv2d(args[0], args[1],
                           args[2] if len(args) > 2 else None,
                           stride=tuple(strides), padding=pairs,
                           dilation=tuple(dil), group=group,
                           pad_mode=auto_pad)


def _h_pool(is_max):
    def h(node, args):
        from .ops import pooling as pool_ops

        a = node.attrs()
        kernel = a["kernel_shape"]
        strides = a.get("strides", [1] * len(kernel))
        pads = a.get("pads", [0] * 2 * len(kernel))
        n = len(kernel)
        pairs = tuple((pads[i], pads[i + n]) for i in range(n))
        return pool_ops.pooling2d(args[0], kernel=tuple(kernel),
                                  stride=tuple(strides),
                                  padding=pairs, is_max=is_max,
                                  pad_mode=a.get("auto_pad", "NOTSET"))
    return h


def _h_batchnorm(node, args):
    from .ops import batchnorm as bn_ops

    a = node.attrs()
    x, scale, bias, mean, var = args[:5]
    mean.requires_grad = mean.stores_grad = False
    var.requires_grad = var.stores_grad = False
    return bn_ops.batchnorm2d(x, scale, bias, mean, var,
                              momentum=a.get("momentum", 0.9),
                              eps=a.get("epsilon", 1e-5))


def _h_reshape(node, args):
    shape = _static_ints(args[1])
    data_shape = args[0].shape
    # ONNX semantics: 0 -> copy input dim
    shape = [data_shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return autograd.reshape(args[0], shape)


def _h_transpose(node, args):
    perm = node.attrs().get("perm")
    return autograd.transpose(args[0], perm)


def _h_concat(node, args):
    return autograd.cat(args, axis=node.attrs().get("axis", 0))


def _h_softmax(node, args):
    return autograd.softmax(args[0], axis=node.attrs().get("axis", -1))


def _h_flatten(node, args):
    return autograd.flatten(args[0], axis=node.attrs().get("axis", 1))


def _h_squeeze(node, args):
    axes = node.attrs().get("axes")
    if axes is None and len(args) > 1 and args[1] is not None:
        axes = _static_ints(args[1])
    return autograd.squeeze(args[0], tuple(axes) if axes else None)


def _h_unsqueeze(node, args):
    axes = node.attrs().get("axes")
    if axes is None:
        axes = _static_ints(args[1])
    return autograd.unsqueeze(args[0], tuple(axes))


def _h_gather(node, args):
    axis = node.attrs().get("axis", 0)
    idx = args[1]
    return _op(lambda x, i, axis=axis: jnp.take(x, i.astype(jnp.int32),
                                                axis=axis),
               args[0], idx, _name="Gather")


def _h_slice(node, args):
    a = node.attrs()
    if "starts" in a:  # opset < 10
        starts, ends = a["starts"], a["ends"]
        axes = a.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    else:
        starts = _static_ints(args[1])
        ends = _static_ints(args[2])
        axes = _static_ints(args[3]) if len(args) > 3 and args[3] is not None \
            else list(range(len(starts)))
        steps = _static_ints(args[4]) if len(args) > 4 and args[4] is not None \
            else [1] * len(starts)

    def f(x):
        idx = [slice(None)] * x.ndim
        for s, e, ax, st in zip(starts, ends, axes, steps):
            idx[ax] = slice(s, None if e >= 2**31 - 1 else e, st)
        return x[tuple(idx)]

    return _op(f, args[0], _name="Slice")


def _h_split(node, args):
    a = node.attrs()
    axis = a.get("axis", 0)
    parts = a.get("split")
    if parts is None and len(args) > 1 and args[1] is not None:
        parts = _static_ints(args[1])
    if parts is None:
        n = len(node.output)
        size = args[0].shape[axis]
        parts = [size // n] * n
    return autograd.split(args[0], axis, parts)


def _h_cast(node, args):
    to = onnx_pb.DTYPE_TO_NP[node.attrs()["to"]]
    return autograd.cast(args[0], to)


def _h_clip(node, args):
    a = node.attrs()
    lo = a.get("min")
    hi = a.get("max")
    if lo is None and len(args) > 1 and args[1] is not None:
        lo = float(_np(args[1]))
    if hi is None and len(args) > 2 and args[2] is not None:
        hi = float(_np(args[2]))
    return autograd.clip(args[0], lo, hi)


def _h_reduce(fn):
    def h(node, args):
        a = node.attrs()
        axes = a.get("axes")
        if axes is None and len(args) > 1 and args[1] is not None:
            axes = _static_ints(args[1])
        keepdims = bool(a.get("keepdims", 1))
        # ax/keepdims ride op.params so a re-export of the imported
        # graph (sonnx._dec_reduce_mean) reproduces the node faithfully
        return _op(lambda x, ax, keepdims: fn(x, axis=ax,
                                              keepdims=keepdims),
                   args[0], _name=node.op_type,
                   ax=tuple(axes) if axes else None, keepdims=keepdims)
    return h


def _h_constant(node, args):
    t = node.attrs()["value"]
    arr = t.to_numpy()
    return tensor.from_numpy(arr, _rep_device())


def _h_constant_of_shape(node, args):
    shape = _static_ints(args[0])
    value = node.attrs().get("value")
    fill = value.to_numpy().reshape(-1)[0] if value is not None else 0.0
    arr = np.full(shape, fill)
    return tensor.from_numpy(arr, _rep_device())


def _h_shape(node, args):
    return tensor.from_numpy(np.asarray(args[0].shape, np.int64),
                             _rep_device())


def _h_expand(node, args):
    shape = _static_ints(args[1])
    return _op(lambda x: jnp.broadcast_to(
        x, np.broadcast_shapes(x.shape, tuple(shape))), args[0],
        _name="Expand")


def _h_dropout(node, args):
    ratio = node.attrs().get("ratio", 0.5)
    if len(args) > 1 and args[1] is not None:
        ratio = float(_np(args[1]))
    return autograd.dropout(args[0], ratio)


def _h_layernorm(node, args):
    a = node.attrs()
    return autograd.layer_norm(args[0], args[1], args[2],
                               axis=a.get("axis", -1),
                               eps=a.get("epsilon", 1e-5))


def _h_where(node, args):
    return autograd.where_op(args[0], args[1], args[2])


def _h_onehot(node, args):
    axis = node.attrs().get("axis", -1)
    depth = int(_np(args[1]).reshape(-1)[0])
    off_on = _np(args[2]).reshape(-1)

    def f(idx):
        oh = (jnp.arange(depth) == idx[..., None].astype(jnp.int32))
        out = jnp.where(oh, off_on[1], off_on[0]).astype(jnp.float32)
        if axis != -1:
            out = jnp.moveaxis(out, -1, axis)
        return out

    return _op(f, args[0], _name="OneHot")


def _h_range(node, args):
    start, limit, delta = (float(_np(a).reshape(-1)[0]) for a in args[:3])
    return tensor.from_numpy(np.arange(start, limit, delta), _rep_device())


def _h_tile(node, args):
    reps = _static_ints(args[1])
    return _op(lambda x: jnp.tile(x, tuple(reps)), args[0], _name="Tile")


def _h_pad(node, args):
    a = node.attrs()
    pads = a.get("pads")
    if pads is None:
        pads = _static_ints(args[1])
    n = len(pads) // 2
    pad_width = tuple((pads[i], pads[i + n]) for i in range(n))
    mode = a.get("mode", "constant")
    if isinstance(mode, bytes):
        mode = mode.decode()
    # negative pads are legal ONNX (crop that edge): apply them as a
    # slice, keep only non-negative widths for jnp.pad
    pos = tuple((max(lo, 0), max(hi, 0)) for lo, hi in pad_width)
    crop = tuple(
        slice(-lo if lo < 0 else None, hi if hi < 0 else None)
        for lo, hi in pad_width)
    has_neg = any(lo < 0 or hi < 0 for lo, hi in pad_width)

    def apply(x, padder):
        if has_neg:
            x = x[crop]
        return padder(x)

    if mode == "constant":
        # opset>=11 carries the pad value as the third input; earlier
        # opsets as the 'value' attribute.
        value = a.get("value", 0.0)
        if len(args) > 2 and args[2] is not None:
            value = float(_np(args[2]).reshape(-1)[0])
        return _op(lambda x: apply(x, lambda v: jnp.pad(
            v, pos, constant_values=value)), args[0], _name="Pad")
    if mode in ("reflect", "edge"):
        return _op(lambda x: apply(x, lambda v: jnp.pad(v, pos, mode=mode)),
                   args[0], _name="Pad")
    raise NotImplementedError(f"ONNX Pad mode {mode!r} is not supported")


def _h_global_avg_pool(node, args):
    return autograd.reduce_mean(args[0], axes=(2, 3), keepdims=True)


def _h_global_max_pool(node, args):
    return _op(lambda x: jnp.max(x, axis=tuple(range(2, x.ndim)),
                                 keepdims=True),
               args[0], _name="GlobalMaxPool")


def _h_upsample(node, args):
    """Legacy Upsample (deprecated at opset 10 in favor of Resize):
    scales as attr (opset 7) or second input (9); nearest mode uses the
    asymmetric/floor indexing this op predates Resize's ctm zoo with."""
    a = node.attrs()
    mode = a.get("mode", "nearest")
    if isinstance(mode, bytes):
        mode = mode.decode()
    scales = a.get("scales")
    if scales is None:
        scales = [float(s) for s in _np(args[1]).reshape(-1)]
    x = args[0]
    out_shape = tuple(int(np.floor(d * s))
                      for d, s in zip(x.shape, scales))
    if out_shape[:2] != tuple(x.shape[:2]):
        raise NotImplementedError(
            "ONNX Upsample on batch/channel dims is not supported")
    if mode == "nearest":
        def f(v):
            for ax in range(2, v.ndim):
                n_in, n_out = v.shape[ax], out_shape[ax]
                if n_in == n_out:
                    continue
                idx = jnp.clip(jnp.floor(
                    jnp.arange(n_out, dtype=jnp.float32)
                    / scales[ax]).astype(jnp.int32), 0, n_in - 1)
                v = jnp.take(v, idx, axis=ax)
            return v

        return _op(f, x, _name="Upsample")
    if mode in ("linear", "bilinear"):
        # separable lerp with ASYMMETRIC coordinates (src = dst/scale),
        # the Upsample-7/9 / ORT semantics — jax.image.resize('linear')
        # would silently substitute half-pixel centers (advisor r04)
        def f(v):
            for ax in range(2, v.ndim):
                n_in, n_out = v.shape[ax], out_shape[ax]
                if n_in == n_out:
                    continue
                src = jnp.arange(n_out, dtype=jnp.float32) / scales[ax]
                i0 = jnp.clip(jnp.floor(src).astype(jnp.int32), 0,
                              n_in - 1)
                i1 = jnp.minimum(i0 + 1, n_in - 1)
                w = (src - i0.astype(jnp.float32)).astype(v.dtype)
                shape = [1] * v.ndim
                shape[ax] = n_out
                w = w.reshape(shape)
                v = jnp.take(v, i0, axis=ax) * (1 - w) \
                    + jnp.take(v, i1, axis=ax) * w
            return v

        return _op(f, x, _name="Upsample")
    raise NotImplementedError(f"ONNX Upsample mode {mode!r}")


def _h_conv_transpose(node, args):
    from .ops import conv as conv_ops

    a = node.attrs()
    kernel = a.get("kernel_shape", list(args[1].shape[2:]))
    n = len(kernel)
    if a.get("auto_pad", "NOTSET") not in ("NOTSET", b"NOTSET"):
        raise NotImplementedError(
            "ONNX ConvTranspose auto_pad modes are not supported "
            "(exporters emit explicit pads)")
    if a.get("output_shape") is not None:
        raise NotImplementedError(
            "ONNX ConvTranspose output_shape is not supported; use "
            "pads/output_padding")
    pads = a.get("pads", [0] * 2 * n)
    pairs = tuple((pads[i], pads[i + n]) for i in range(n))
    return conv_ops.conv_transpose2d(
        args[0], args[1], args[2] if len(args) > 2 else None,
        stride=tuple(a.get("strides", [1] * n)), padding=pairs,
        dilation=tuple(a.get("dilations", [1] * n)),
        group=a.get("group", 1),
        output_padding=tuple(a.get("output_padding", [0] * n)))


def _h_arg_extremum(fn, name):
    def h(node, args):
        a = node.attrs()
        axis = a.get("axis", 0)
        keepdims = bool(a.get("keepdims", 1))
        if a.get("select_last_index", 0):
            raise NotImplementedError(
                f"ONNX {name} select_last_index=1 is not supported")
        # int32, not int64: x64 is disabled in this runtime, so an
        # int64 cast would silently truncate anyway and warn every call
        return _op(lambda x: fn(x, axis=axis,
                                keepdims=keepdims).astype(jnp.int32),
                   args[0], _name=name)
    return h


_h_argmax = _h_arg_extremum(jnp.argmax, "ArgMax")


def _h_topk(node, args):
    a = node.attrs()
    axis = a.get("axis", -1)
    largest = bool(a.get("largest", 1))
    if not a.get("sorted", 1):
        raise NotImplementedError("ONNX TopK sorted=0 is not supported")
    k = int(_np(args[1]).reshape(-1)[0])

    def f(x):
        y = jnp.moveaxis(x, axis, -1)
        if largest:
            vals, idx = jax.lax.top_k(y, k)
        else:
            vals, idx = jax.lax.top_k(-y, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, axis),
                jnp.moveaxis(idx.astype(jnp.int32), -1, axis))

    return _op(f, args[0], _name="TopK")


def _h_einsum(node, args):
    eq = node.attrs()["equation"]
    if isinstance(eq, bytes):
        eq = eq.decode()
    return _op(lambda *xs: jnp.einsum(eq, *xs), *args, _name="Einsum")


# ---- ONNX RNN family -> ops/rnn.py packed-weight stack --------------------
# Gate-order maps from ONNX's conventions onto the cuDNN order the
# packed buffer uses (ops/rnn.py): LSTM iofc -> ifgo; GRU zrh -> rzn.
_ONNX_GATE_PERM = {"lstm": [0, 2, 3, 1], "gru": [1, 0, 2],
                   "vanilla_tanh": [0], "vanilla_relu": [0]}
_ONNX_DEFAULT_ACTS = {
    "lstm": ("sigmoid", "tanh", "tanh"),
    "gru": ("sigmoid", "tanh"),
    "vanilla_tanh": ("tanh",), "vanilla_relu": ("relu",)}


def _h_rnn(onnx_kind):
    def h(node, args):
        from .ops import rnn as rnn_ops

        a = node.attrs()
        H = int(a["hidden_size"])
        direction = a.get("direction", "forward")
        if isinstance(direction, bytes):
            direction = direction.decode()
        n_dirs = 2 if direction == "bidirectional" else 1
        if a.get("layout", 0):
            raise NotImplementedError(
                "ONNX RNN layout=1 is not supported (PyTorch/TF "
                "exporters emit layout=0)")
        if a.get("clip") is not None:
            raise NotImplementedError("ONNX RNN clip is not supported")
        if onnx_kind == "lstm":
            if len(args) > 7 and args[7] is not None:
                raise NotImplementedError(
                    "ONNX LSTM peephole weights (input P) are not "
                    "supported")
            if a.get("input_forget", 0):
                raise NotImplementedError(
                    "ONNX LSTM input_forget=1 is not supported")
        acts = a.get("activations")
        if acts is not None:
            acts = tuple(
                (x.decode() if isinstance(x, bytes) else x).lower()
                for x in acts)
        mode = onnx_kind
        if onnx_kind == "rnn":
            per_dir_acts = acts or ("tanh",) * n_dirs
            if len(set(per_dir_acts)) > 1:
                raise NotImplementedError(
                    "ONNX RNN with different activations per direction "
                    f"({per_dir_acts}) is not supported")
            first = per_dir_acts[0]
            if first not in ("tanh", "relu"):
                raise NotImplementedError(
                    f"ONNX RNN activation {first!r} is not supported")
            mode = "vanilla_relu" if first == "relu" else "vanilla_tanh"
        elif acts:
            # both directions must carry the default activation triple
            want = _ONNX_DEFAULT_ACTS[mode] * n_dirs
            if acts != want[:len(acts)] or len(acts) < len(want):
                raise NotImplementedError(
                    f"ONNX {node.op_type} non-default activations "
                    f"{acts} are not supported")

        seq_lens = args[4] if len(args) > 4 else None
        T = args[0].shape[0]
        if seq_lens is not None:
            sl = _np(seq_lens).reshape(-1)
            if not (sl == T).all():
                raise NotImplementedError(
                    "ONNX RNN per-row sequence_lens are not supported "
                    "(all rows must equal the padded length)")
        if mode == "gru" and not a.get("linear_before_reset", 0):
            return _gru_lbr0(node, args, H, direction)

        X, W, R = args[0], args[1], args[2]
        B = args[3] if len(args) > 3 else None
        h0 = args[5] if len(args) > 5 else None
        c0 = args[6] if len(args) > 6 else None
        T, bsz, inp = X.shape
        D = n_dirs
        # direction="reverse" = flip time, run the forward handle, flip
        # back (half the cost of emulating via a bidirectional handle;
        # Y_h/Y_c of a reverse scan are the states after its LAST step,
        # which the flipped forward run yields directly)
        if direction == "reverse":
            X = _op(lambda x: jnp.flip(x, 0), X, _name="Flip")
        G = rnn_ops._GATES[mode]
        perm = _ONNX_GATE_PERM[mode]
        row_idx = np.concatenate(
            [np.arange(p * H, (p + 1) * H) for p in perm])

        handle = rnn_ops.RNNHandle(
            inp, H, num_layers=1, mode=mode,
            bidirectional=direction == "bidirectional")

        def pack_dir(d):
            wd = autograd.gather(_slice0(W, d), 0, row_idx)
            rd = autograd.gather(_slice0(R, d), 0, row_idx)
            if B is not None:
                bd = _slice0(B, d)
                b_ih = autograd.gather(bd, 0, row_idx)
                b_hh = autograd.gather(bd, 0, row_idx + G * H)
            else:
                z = tensor.from_numpy(np.zeros(G * H, np.float32),
                                      _rep_device())
                b_ih = b_hh = z
            return [autograd.reshape(wd, (-1,)),
                    autograd.reshape(rd, (-1,)),
                    autograd.reshape(b_ih, (-1,)),
                    autograd.reshape(b_hh, (-1,))]

        pieces = []
        for d in range(D):
            pieces.extend(pack_dir(d))
        w_flat = autograd.cat(pieces, 0) if len(pieces) > 1 else pieces[0]

        zeros_h = tensor.from_numpy(np.zeros((D, bsz, H), np.float32),
                                    _rep_device())
        hx = h0 if h0 is not None else zeros_h
        cx = c0 if c0 is not None else zeros_h

        y, hy, cy = rnn_ops.rnn_forward(X, hx, cx, w_flat, handle)
        # y: (T, B, D*H) -> ONNX Y (T, D, B, H)
        if direction == "reverse":
            y = _op(lambda v: jnp.flip(v, 0), y, _name="Flip")
        y = autograd.reshape(y, (T, bsz, D, H))
        Y = autograd.transpose(y, (0, 2, 1, 3))
        if mode == "lstm":
            return Y, hy, cy
        return Y, hy

    return h


def _slice0(t, i):
    """t[i] along axis 0 as an autograd op (keeps initializer grads)."""
    return autograd.reshape(
        autograd.gather(t, 0, np.asarray([i])), tuple(t.shape[1:]))


def _gru_lbr0(node, args, H, direction):
    """ONNX GRU with linear_before_reset=0 (the ONNX default): the
    candidate gate applies the reset BEFORE the recurrent matmul —
    n = tanh(Wn x + Wbn + Rn (r⊙h) + Rbn) — a different functional form
    from the cuDNN cell (which is lbr=1), so it runs as its own scan
    instead of mapping onto the packed stack."""
    X, W, R = args[0], args[1], args[2]
    B = args[3] if len(args) > 3 else None
    h0 = args[5] if len(args) > 5 else None
    T, bsz, _inp = X.shape
    D = 2 if direction == "bidirectional" else 1
    dirs = (["fwd", "rev"] if direction == "bidirectional"
            else (["rev"] if direction == "reverse" else ["fwd"]))

    def f(x, w, r, *rest):
        b = rest[0] if B is not None else None
        h_init = rest[-1] if h0 is not None else None
        ys, hts = [], []
        for di, dname in enumerate(dirs):
            wz, wr, wn = jnp.split(w[di], 3, axis=0)
            rz, rr, rn = jnp.split(r[di], 3, axis=0)
            if b is not None:
                wbz, wbr, wbn, rbz, rbr, rbn = jnp.split(b[di], 6)
            else:
                wbz = wbr = wbn = rbz = rbr = rbn = jnp.zeros(H, x.dtype)
            hstart = (h_init[di] if h_init is not None
                      else jnp.zeros((bsz, H), x.dtype))

            def cell(h, xt):
                z = jax.nn.sigmoid(xt @ wz.T + wbz + h @ rz.T + rbz)
                rg = jax.nn.sigmoid(xt @ wr.T + wbr + h @ rr.T + rbr)
                n = jnp.tanh(xt @ wn.T + wbn + (rg * h) @ rn.T + rbn)
                h = (1 - z) * n + z * h
                return h, h

            hT, y = jax.lax.scan(cell, hstart, x,
                                 reverse=dname == "rev")
            ys.append(y)
            hts.append(hT)
        Y = jnp.stack(ys, axis=1)               # (T, D, B, H)
        hy = jnp.stack(hts, axis=0)             # (D, B, H)
        return Y, hy

    ins = [X, W, R]
    if B is not None:
        ins.append(B)
    if h0 is not None:
        ins.append(h0)
    return _op(f, *ins, _name="GRU")




def _h_resize(node, args):
    """ONNX Resize: mode nearest with coordinate_transformation_mode in
    {half_pixel (spec default) + round_prefer_floor (spec default),
    asymmetric + floor (torch's interpolate export)}, and mode
    linear/cubic with half_pixel.  Scales or sizes; only trailing
    spatial dims may resize."""
    a = node.attrs()
    mode = a.get("mode", "nearest")
    if isinstance(mode, bytes):
        mode = mode.decode()
    ctm = a.get("coordinate_transformation_mode", "half_pixel")
    if isinstance(ctm, bytes):
        ctm = ctm.decode()
    nearest_mode = a.get("nearest_mode", "round_prefer_floor")
    if isinstance(nearest_mode, bytes):
        nearest_mode = nearest_mode.decode()
    x = args[0]
    # opset 11+: inputs are (X, roi, scales, sizes)
    scales = args[2] if len(args) > 2 and args[2] is not None else None
    sizes = args[3] if len(args) > 3 and args[3] is not None else None
    if sizes is not None:
        out_shape = tuple(int(v) for v in _np(sizes).reshape(-1))
        scale_per_dim = [o / d for o, d in zip(out_shape, x.shape)]
    elif scales is not None:
        scale_per_dim = [float(s) for s in _np(scales).reshape(-1)]
        # spec: output dim = floor(input dim * scale)
        out_shape = tuple(int(np.floor(d * s))
                          for d, s in zip(x.shape, scale_per_dim))
    else:
        raise NotImplementedError("ONNX Resize needs scales or sizes")
    if out_shape[:2] != tuple(x.shape[:2]):
        raise NotImplementedError(
            "ONNX Resize on batch/channel dims is not supported")
    if mode == "nearest":
        combo = (ctm, nearest_mode)
        if combo not in (("asymmetric", "floor"),
                         ("half_pixel", "round_prefer_floor")):
            raise NotImplementedError(
                f"ONNX Resize nearest supports asymmetric+floor and "
                f"half_pixel+round_prefer_floor, got {ctm}+{nearest_mode}")

        def f(v):
            for ax in range(2, v.ndim):
                n_in, n_out = v.shape[ax], out_shape[ax]
                if n_in == n_out:
                    continue
                sc = scale_per_dim[ax]
                pos = jnp.arange(n_out, dtype=jnp.float32)
                if ctm == "asymmetric":
                    # x_orig = x / scale; floor
                    idx = jnp.floor(pos / sc)
                else:
                    # half_pixel: x_orig = (x + 0.5)/scale - 0.5;
                    # round_prefer_floor == ceil(v - 0.5)
                    idx = jnp.ceil((pos + 0.5) / sc - 0.5 - 0.5)
                idx = jnp.clip(idx.astype(jnp.int32), 0, n_in - 1)
                v = jnp.take(v, idx, axis=ax)
            return v

        return _op(f, x, _name="Resize")
    if mode == "linear":
        if ctm != "half_pixel":
            raise NotImplementedError(
                f"ONNX Resize linear supports half_pixel only, got {ctm}")
        if a.get("antialias", 0):
            raise NotImplementedError(
                "ONNX Resize antialias=1 is not supported")
        # antialias=False: ONNX defaults to plain interpolation on
        # downscale; jax.image.resize would antialias by default
        return _op(lambda v: jax.image.resize(
            v, out_shape, method="linear", antialias=False),
            x, _name="Resize")
    if mode == "cubic":
        # jax's cubic kernel is Keys a=-0.5; ONNX/torch/ORT default
        # cubic_coeff_a=-0.75 — silently substituting one for the other
        # ships wrong activations, so refuse rather than approximate
        raise NotImplementedError(
            "ONNX Resize mode=cubic is not supported (jax's Keys "
            "a=-0.5 kernel differs from ONNX's default "
            "cubic_coeff_a=-0.75)")
    raise NotImplementedError(f"ONNX Resize mode {mode!r}")


def _h_instance_norm(node, args):
    eps = node.attrs().get("epsilon", 1e-5)

    def f(x, s, b):
        ax = tuple(range(2, x.ndim))
        mu = jnp.mean(x, axis=ax, keepdims=True)
        var = jnp.var(x, axis=ax, keepdims=True)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mu) / jnp.sqrt(var + eps)) * s.reshape(shape) \
            + b.reshape(shape)

    return _op(f, args[0], args[1], args[2], _name="InstanceNormalization")


def _h_prelu(node, args):
    def f(x, slope):
        # ONNX PRelu broadcast is UNIDIRECTIONAL (trailing-aligned);
        # torch exporters additionally rely on a (C,) slope applying
        # per channel on NCHW.  Reshape to the channel axis only when
        # trailing alignment can't claim it (ambiguity resolves to the
        # spec's own rule).
        s = slope
        if s.ndim == 1 and x.ndim > 2 and s.shape[0] == x.shape[1] \
                and s.shape[0] != x.shape[-1]:
            s = s.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x >= 0, x, x * s)

    return _op(f, args[0], args[1], _name="PRelu")


def _h_cumsum(node, args):
    a = node.attrs()
    if a.get("exclusive", 0) or a.get("reverse", 0):
        raise NotImplementedError(
            "ONNX CumSum exclusive/reverse are not supported")
    axis = int(_np(args[1]).reshape(-1)[0])
    return _op(lambda x: jnp.cumsum(x, axis=axis), args[0],
               _name="CumSum")


def _h_depth_space(to_space):
    def h(node, args):
        bs = int(node.attrs()["blocksize"])
        mode = node.attrs().get("mode", "DCR")
        if isinstance(mode, bytes):
            mode = mode.decode()

        def f(x):
            n, c, hh, ww = x.shape
            if to_space:
                if mode == "DCR":
                    y = x.reshape(n, bs, bs, c // (bs * bs), hh, ww)
                    y = y.transpose(0, 3, 4, 1, 5, 2)
                else:  # CRD
                    y = x.reshape(n, c // (bs * bs), bs, bs, hh, ww)
                    y = y.transpose(0, 1, 4, 2, 5, 3)
                return y.reshape(n, c // (bs * bs), hh * bs, ww * bs)
            y = x.reshape(n, c, hh // bs, bs, ww // bs, bs)
            y = y.transpose(0, 3, 5, 1, 2, 4)
            return y.reshape(n, c * bs * bs, hh // bs, ww // bs)

        return _op(f, args[0],
                   _name="DepthToSpace" if to_space else "SpaceToDepth")
    return h


def _h_gather_elements(node, args):
    axis = node.attrs().get("axis", 0)
    # indices stay a graph input (runtime indices from ArgMax/TopK are
    # the common pattern; eager _np would break under tracing)
    return _op(lambda x, i: jnp.take_along_axis(
        x, i.astype(jnp.int32), axis=axis),
        args[0], args[1], _name="GatherElements")


def _h_trilu(node, args):
    """Trilu-14: upper/lower triangular part of the last two dims; the
    optional second input is the diagonal offset k.  Constant k (the
    form HF causal-mask exports emit — an initializer or Constant
    output) folds into the mask at build time; a RUNTIME-computed k
    (e.g. Shape-arithmetic feeding Trilu, or any k under jit tracing,
    where ``_np`` would die on the tracer) stays a graph input and the
    mask comparison traces through jnp (round-6 fix)."""
    upper = bool(node.attrs().get("upper", 1))

    def f(x, k):
        r, c = x.shape[-2], x.shape[-1]
        rows = jnp.arange(r)[:, None]
        cols = jnp.arange(c)[None, :]
        mask = (cols - rows >= k) if upper else (cols - rows <= k)
        return jnp.where(mask, x, jnp.zeros((), x.dtype))

    if len(args) <= 1:
        return _op(lambda x: f(x, 0), args[0], _name="Trilu")
    try:
        k = int(_np(args[1]).reshape(-1)[0])
    except Exception:
        # traced/runtime k: jnp comparisons handle a traced scalar
        return _op(
            lambda x, kt: f(x, kt.reshape(-1)[0].astype(jnp.int32)),
            args[0], args[1], _name="Trilu")
    return _op(lambda x: f(x, k), args[0], _name="Trilu")


def _scatter_ref(ref, upd, reduction, opname):
    if reduction == "none":
        return ref.set(upd)
    if reduction == "add":
        return ref.add(upd)
    if reduction == "mul":
        return ref.multiply(upd)
    if reduction == "max":
        return ref.max(upd)
    if reduction == "min":
        return ref.min(upd)
    raise NotImplementedError(
        f"ONNX {opname} reduction {reduction!r} is not supported")


def _h_scatter_nd(node, args):
    """ScatterND-11/16/18 (none/add/mul/max/min reductions).  Indices
    stay a graph input (runtime indices are the detection-model
    pattern); with duplicate indices and reduction 'none' the spec
    leaves the result undefined — this backend takes XLA's scatter
    order."""
    red = node.attrs().get("reduction", "none")
    if isinstance(red, bytes):
        red = red.decode()

    def f(data, idx, upd):
        ii = tuple(jnp.moveaxis(idx.astype(jnp.int32), -1, 0))
        return _scatter_ref(data.at[ii], upd, red, "ScatterND")

    return _op(f, args[0], args[1], args[2], _name="ScatterND")


def _h_scatter_elements(node, args):
    """ScatterElements-11/16/18 (and legacy Scatter-9): the scatter
    twin of GatherElements — per-element writes along ``axis``."""
    axis = node.attrs().get("axis", 0)
    red = node.attrs().get("reduction", "none")
    if isinstance(red, bytes):
        red = red.decode()

    def f(data, idx, upd):
        idx = idx.astype(jnp.int32)
        grids = jnp.indices(idx.shape)
        ii = tuple(idx if d == (axis % data.ndim) else grids[d]
                   for d in range(data.ndim))
        return _scatter_ref(data.at[ii], upd, red, "ScatterElements")

    return _op(f, args[0], args[1], args[2], _name="ScatterElements")


def _h_gather_nd(node, args):
    """GatherND-11/12/13 with batch_dims."""
    b = int(node.attrs().get("batch_dims", 0))

    def f(data, idx):
        idx = idx.astype(jnp.int32)

        def core(d, i):
            return d[tuple(jnp.moveaxis(i, -1, 0))]

        fn = core
        for _ in range(b):
            fn = jax.vmap(fn)
        return fn(data, idx)

    return _op(f, args[0], args[1], _name="GatherND")


def _h_nonzero(node, args):
    """NonZero-9/13: (rank, N) indices of nonzero elements.  The output
    shape is DATA-DEPENDENT, which XLA's static-shape model cannot
    express — the op therefore works in eager execution (the normal
    path for an imported ONNX graph) and raises jax's concretization
    error inside jit/graph mode.  Index dtype is int32, the documented
    x64-disabled divergence (see _h_arg_extremum)."""
    def f(x):
        return jnp.stack(jnp.nonzero(x)).astype(jnp.int32)

    return _op(f, args[0], _name="NonZero")


def _h_group_norm(node, args):
    """GroupNormalization-18/21.  Opset 18 wrote scale/bias per GROUP
    (num_groups,); opset 21 fixed them to per-channel (C,) — both
    layouts are accepted, disambiguated by length (matching ORT)."""
    a = node.attrs()
    eps = a.get("epsilon", 1e-5)
    g = int(a["num_groups"])

    def f(x, s, b):
        n, c = x.shape[0], x.shape[1]
        if c % g:
            raise ValueError(
                f"GroupNormalization: channels {c} not divisible by "
                f"num_groups {g}")
        xg = x.reshape((n, g, c // g) + x.shape[2:])
        ax = tuple(range(2, xg.ndim))
        mu = jnp.mean(xg, axis=ax, keepdims=True)
        var = jnp.var(xg, axis=ax, keepdims=True)
        y = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
        if s.shape[0] == c:
            pass  # per-channel (opset 21)
        elif s.shape[0] == g:  # per-group (opset 18): expand to C
            s = jnp.repeat(s, c // g)
            b = jnp.repeat(b, c // g)
        else:
            raise ValueError(
                f"GroupNormalization scale length {s.shape[0]} is "
                f"neither C={c} nor num_groups={g}")
        shape = (1, c) + (1,) * (x.ndim - 2)
        return y * s.reshape(shape) + b.reshape(shape)

    return _op(f, args[0], args[1], args[2], _name="GroupNormalization")


# subgraph-carrying control-flow ops, dispatched in _exec_nodes (they
# need the enclosing env for outer-scope capture, so they live outside
# the flat handler table); the conformance sweep counts them as
# supported ops
_CONTROL_FLOW_OPS = ("If", "Loop", "Scan")

_ONNX_OPS = {
    "Add": _handle_binary(jnp.add),
    "Sub": _handle_binary(jnp.subtract),
    "Mul": _handle_binary(jnp.multiply),
    "Div": _handle_binary(jnp.divide),
    "Pow": _handle_binary(jnp.power),
    "MatMul": _handle_binary(jnp.matmul),
    "Equal": _handle_binary(lambda a, b: (a == b)),
    "Greater": _handle_binary(lambda a, b: (a > b)),
    "Less": _handle_binary(lambda a, b: (a < b)),
    "Min": _handle_binary(jnp.minimum),
    "Max": _handle_binary(jnp.maximum),
    "Relu": _handle_unary(lambda x: jnp.maximum(x, 0)),
    "Sigmoid": _handle_unary(lambda x: 1 / (1 + jnp.exp(-x))),
    "Tanh": _handle_unary(jnp.tanh),
    "Erf": _handle_unary(lambda x: jnp.asarray(__import__("jax").lax.erf(x))),
    "Exp": _handle_unary(jnp.exp),
    "Log": _handle_unary(jnp.log),
    "Sqrt": _handle_unary(jnp.sqrt),
    "Neg": _handle_unary(jnp.negative),
    "Abs": _handle_unary(jnp.abs),
    "Reciprocal": _handle_unary(jnp.reciprocal),
    "Identity": _handle_unary(lambda x: x),
    "Floor": _handle_unary(jnp.floor),
    "Ceil": _handle_unary(jnp.ceil),
    # ONNX Gelu default is the EXACT erf form (approximate="none");
    # jax.nn.gelu defaults to tanh — honor the attribute
    "Gelu": lambda node, args: autograd.gelu(
        args[0],
        approximate=node.attrs().get("approximate", "none") == "tanh"),
    "LeakyRelu": lambda node, args: autograd.leakyrelu(
        args[0], node.attrs().get("alpha", 0.01)),
    "Elu": lambda node, args: autograd.elu(
        args[0], node.attrs().get("alpha", 1.0)),
    "Selu": lambda node, args: autograd.selu(args[0]),
    "Softplus": lambda node, args: autograd.softplus(args[0]),
    "Gemm": _h_gemm,
    "Conv": _h_conv,
    "MaxPool": _h_pool(True),
    "AveragePool": _h_pool(False),
    "GlobalAveragePool": _h_global_avg_pool,
    "GlobalMaxPool": _h_global_max_pool,
    "Upsample": _h_upsample,
    "BatchNormalization": _h_batchnorm,
    "Reshape": _h_reshape,
    "Transpose": _h_transpose,
    "Concat": _h_concat,
    "Softmax": _h_softmax,
    "Flatten": _h_flatten,
    "Squeeze": _h_squeeze,
    "Unsqueeze": _h_unsqueeze,
    "Gather": _h_gather,
    "Slice": _h_slice,
    "Split": _h_split,
    "Cast": _h_cast,
    "Clip": _h_clip,
    "ReduceMean": _h_reduce(jnp.mean),
    "ReduceSum": _h_reduce(jnp.sum),
    "ReduceMax": _h_reduce(jnp.max),
    "ReduceMin": _h_reduce(jnp.min),
    "Constant": _h_constant,
    "ConstantOfShape": _h_constant_of_shape,
    "Shape": _h_shape,
    "Expand": _h_expand,
    "Dropout": _h_dropout,
    "LayerNormalization": _h_layernorm,
    "Where": _h_where,
    "OneHot": _h_onehot,
    "Range": _h_range,
    "Tile": _h_tile,
    "Pad": _h_pad,
    "ConvTranspose": _h_conv_transpose,
    "Resize": _h_resize,
    "InstanceNormalization": _h_instance_norm,
    "PRelu": _h_prelu,
    "CumSum": _h_cumsum,
    "DepthToSpace": _h_depth_space(True),
    "SpaceToDepth": _h_depth_space(False),
    "GatherElements": _h_gather_elements,
    "Trilu": _h_trilu,
    "ScatterND": _h_scatter_nd,
    "ScatterElements": _h_scatter_elements,
    "GatherND": _h_gather_nd,
    "NonZero": _h_nonzero,
    "GroupNormalization": _h_group_norm,
    "And": _handle_binary(jnp.logical_and),
    "Or": _handle_binary(jnp.logical_or),
    "Xor": _handle_binary(jnp.logical_xor),
    "Not": _handle_unary(jnp.logical_not),
    "GreaterOrEqual": _handle_binary(lambda a, b: (a >= b)),
    "LessOrEqual": _handle_binary(lambda a, b: (a <= b)),
    "Mod": lambda node, args: _handle_binary(
        jnp.fmod if node.attrs().get("fmod", 0) else jnp.mod)(node, args),
    "Sign": _handle_unary(jnp.sign),
    "Round": _handle_unary(jnp.round),
    "Sin": _handle_unary(jnp.sin),
    "Cos": _handle_unary(jnp.cos),
    "Tan": _handle_unary(jnp.tan),
    "Asin": _handle_unary(jnp.arcsin),
    "Acos": _handle_unary(jnp.arccos),
    "Atan": _handle_unary(jnp.arctan),
    "Sinh": _handle_unary(jnp.sinh),
    "Cosh": _handle_unary(jnp.cosh),
    "Asinh": _handle_unary(jnp.arcsinh),
    "Acosh": _handle_unary(jnp.arccosh),
    "Atanh": _handle_unary(jnp.arctanh),
    "IsNaN": _handle_unary(jnp.isnan),
    "IsInf": lambda node, args: _op(
        lambda x, neg, pos: (jnp.isinf(x)
                             & ((pos & (x > 0)) | (neg & (x < 0)))),
        args[0], _name="IsInf",
        neg=bool(node.attrs().get("detect_negative", 1)),
        pos=bool(node.attrs().get("detect_positive", 1))),
    "ReduceLogSum": _h_reduce(
        lambda x, axis, keepdims: jnp.log(
            jnp.sum(x, axis=axis, keepdims=keepdims))),
    # opset-13 Hardmax: one-hot of the argmax along ``axis`` (default
    # -1); the opset<13 flatten-at-axis form is not accepted by modern
    # exporters and is not implemented
    "Hardmax": lambda node, args: _op(
        lambda x, axis: jax.nn.one_hot(
            jnp.argmax(x, axis=axis), x.shape[axis],
            dtype=x.dtype, axis=axis),
        args[0], _name="Hardmax", axis=node.attrs().get("axis", -1)),
    # n-ary elementwise (broadcasting folds pairwise)
    "Sum": lambda node, args: _op(
        lambda *xs: functools.reduce(jnp.add, xs), *args, _name="Sum"),
    "Mean": lambda node, args: _op(
        lambda *xs: functools.reduce(jnp.add, xs) / len(xs), *args,
        _name="Mean"),
    "Size": lambda node, args: _op(
        lambda x: jnp.asarray(x.size, jnp.int32), args[0],
        _name="Size"),
    "EyeLike": lambda node, args: _op(
        lambda x, k, dt: jnp.eye(
            x.shape[0], x.shape[1], k=k,
            dtype=x.dtype if dt is None else onnx_pb.DTYPE_TO_NP[dt]),
        args[0], _name="EyeLike", k=node.attrs().get("k", 0),
        dt=node.attrs().get("dtype")),
    "Softsign": _handle_unary(lambda x: x / (1 + jnp.abs(x))),
    "HardSigmoid": lambda node, args: _op(
        lambda x, alpha, beta: jnp.clip(alpha * x + beta, 0.0, 1.0),
        args[0], _name="HardSigmoid",
        alpha=node.attrs().get("alpha", 0.2),
        beta=node.attrs().get("beta", 0.5)),
    "HardSwish": _handle_unary(
        lambda x: x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)),
    "LogSoftmax": lambda node, args: _op(
        lambda x: jax.nn.log_softmax(x, axis=node.attrs().get("axis",
                                                              -1)),
        args[0], _name="LogSoftmax"),
    "Celu": lambda node, args: _op(
        lambda x, alpha: jnp.maximum(x, 0) + jnp.minimum(
            0, alpha * (jnp.exp(x / alpha) - 1)),
        args[0], _name="Celu", alpha=node.attrs().get("alpha", 1.0)),
    "Mish": _handle_unary(
        lambda x: x * jnp.tanh(jnp.log1p(jnp.exp(x)))),
    "ThresholdedRelu": lambda node, args: _op(
        lambda x, alpha: jnp.where(x > alpha, x, 0.0),
        args[0], _name="ThresholdedRelu",
        alpha=node.attrs().get("alpha", 1.0)),
    "Shrink": lambda node, args: _op(
        lambda x, lambd, bias: jnp.where(
            x > lambd, x - bias, jnp.where(x < -lambd, x + bias, 0.0)),
        args[0], _name="Shrink",
        lambd=node.attrs().get("lambd", 0.5),
        bias=node.attrs().get("bias", 0.0)),
    "ReduceSumSquare": _h_reduce(lambda x, axis, keepdims: jnp.sum(
        x * x, axis=axis, keepdims=keepdims)),
    "ReduceProd": _h_reduce(jnp.prod),
    "ReduceL1": _h_reduce(lambda x, axis, keepdims: jnp.sum(
        jnp.abs(x), axis=axis, keepdims=keepdims)),
    "ReduceL2": _h_reduce(lambda x, axis, keepdims: jnp.sqrt(
        jnp.sum(x * x, axis=axis, keepdims=keepdims))),
    "ReduceLogSumExp": _h_reduce(
        lambda x, axis, keepdims: jax.scipy.special.logsumexp(
            x, axis=axis, keepdims=keepdims)),
    "ArgMin": _h_arg_extremum(jnp.argmin, "ArgMin"),
    "ArgMax": _h_argmax,
    "TopK": _h_topk,
    "Einsum": _h_einsum,
    "LSTM": _h_rnn("lstm"),
    "GRU": _h_rnn("gru"),
    "RNN": _h_rnn("rnn"),
}


# ---------------------------------------------------------------------------
# Frontend: singa tape -> ONNX (reference: SingaFrontend.to_onnx)
# ---------------------------------------------------------------------------

# map our Operation names (autograd op name prefix before '#') to onnx
_EXPORT_OPS = {
    "ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh", "Gelu": "Gelu",
    "Add": "Add", "Sub": "Sub", "Mul": "Mul", "Div": "Div", "Pow": "Pow",
    "Matmul": "MatMul", "AddBias": "Add", "SoftMax": "Softmax",
    "Exp": "Exp", "Log": "Log", "Sqrt": "Sqrt", "Abs": "Abs",
    "Negative": "Neg", "Conv2d": "Conv", "MaxPool2d": "MaxPool",
    "ConvTranspose2d": "ConvTranspose",
    "AvgPool2d": "AveragePool",
    "Flatten": "Flatten", "Reshape": "Reshape", "Transpose": "Transpose",
    "Concat": "Concat", "Identity": "Identity", "Erf": "Erf",
    "LayerNorm": "LayerNormalization", "_Dropout": "Dropout",
}


# -- decomposed export of fused TPU-native ops ------------------------------
# The MXU-fused ops (attention, embedding gather, BERT's mask builders)
# have no single ONNX node; they export as small subgraphs of standard
# ONNX ops that the backend re-imports (roundtrips tested for BERT and
# GPT-2 in tests/test_sonnx_transformers.py).  Each decomposer receives
# an _Emit helper bound to the graph being built and must name its final
# output(s) f"{op.name}_out{i}" so downstream consumers resolve.

def _dec_attention(op, in_names, emit, out_name):
    """Fused (q,k,v[,mask]) attention -> Transpose/MatMul/Mul/(Add)/
    Softmax/MatMul.  The causal variant bakes a static (S,T) additive
    mask (shapes are concrete at export time)."""
    p = getattr(op, "params", {}) or {}
    scale = float(p.get("scale", 1.0))
    causal = bool(p.get("causal", False))
    q_t, k_t = op.src[0][2], op.src[1][2]
    s, t = q_t.shape[-2], k_t.shape[-2]
    u = emit.uniq("Attention")
    kt = f"{u}_kT"
    emit.node("Transpose", [in_names[1]], [kt], perm=[0, 1, 3, 2])
    sc = f"{u}_scores"
    emit.node("MatMul", [in_names[0], kt], [sc])
    cur = f"{u}_scaled"
    emit.node("Mul", [sc, emit.const(f"const_scale_{float(scale)!r}",
                                     np.float32(scale))], [cur])
    if len(in_names) > 3:
        nxt = f"{u}_masked"
        emit.node("Add", [cur, in_names[3]], [nxt])
        cur = nxt
    if causal:
        keep = np.tril(np.ones((s, t), bool))
        window = p.get("window")
        if window is not None:  # sliding-window band
            i, j = np.arange(s)[:, None], np.arange(t)[None, :]
            keep &= (i - j) < int(window)
        cm = np.where(keep, 0.0, -1e9).astype(np.float32)
        nxt = f"{u}_causal"
        # shape-keyed name (window-qualified): every layer shares ONE
        # mask constant
        wtag = "" if window is None else f"_w{int(window)}"
        emit.node("Add", [cur, emit.const(
            f"const_causal_{s}x{t}{wtag}", cm)], [nxt])
        cur = nxt
    pr = f"{u}_probs"
    emit.node("Softmax", [cur], [pr], axis=-1)
    emit.node("MatMul", [pr, in_names[2]], [out_name])


def _dec_embedding(op, in_names, emit, out_name):
    """embedding(ids, W) -> Gather(W, ids) (input order swapped)."""
    emit.node("Gather", [in_names[1], in_names[0]], [out_name], axis=0)


def _dec_repeat_kv(op, in_names, emit, out_name):
    """GQA K/V head broadcast (B, H_kv, S, D) -> (B, H_kv·g, S, D):
    an element-interleaved repeat on axis 1, which in ONNX is
    Reshape(+1 axis) / Tile(g on the new axis) / Reshape(merge) —
    Tile alone would cycle whole-head blocks, the wrong order."""
    g = int((getattr(op, "params", {}) or {}).get("repeats", 1))
    b, hkv, s, d = op.src[0][2].shape
    u = emit.uniq("RepeatKV")
    r5 = f"{u}_r5d"
    emit.node("Reshape", [in_names[0], emit.const(
        f"const_shape_{b}x{hkv}x1x{s}x{d}",
        np.asarray([b, hkv, 1, s, d], np.int64))], [r5])
    t5 = f"{u}_tiled"
    emit.node("Tile", [r5, emit.const(
        f"const_reps_11{g}11", np.asarray([1, 1, g, 1, 1], np.int64))],
        [t5])
    emit.node("Reshape", [t5, emit.const(
        f"const_shape_{b}x{hkv * g}x{s}x{d}",
        np.asarray([b, hkv * g, s, d], np.int64))], [out_name])


def _dec_attn_mask(op, in_names, emit, out_name):
    """BERT (1-m)*-1e9 [:,None,None,:] -> Sub/Mul/Unsqueeze."""
    u = emit.uniq("AttnMask")
    t1, t2 = f"{u}_inv", f"{u}_scaled"
    emit.node("Sub", [emit.const("const_one_f32", np.float32(1.0)),
                      in_names[0]], [t1])
    emit.node("Mul", [t1, emit.const("const_neg1e9_f32",
                                     np.float32(-1e9))], [t2])
    # opset >= 13: axes is an INPUT, not an attribute
    emit.node("Unsqueeze",
              [t2, emit.const("const_axes_1_2",
                              np.asarray([1, 2], np.int64))], [out_name])


def _dec_first_token(op, in_names, emit, out_name):
    """x[:, 0, :] -> Gather(x, 0, axis=1) (scalar index drops the axis)."""
    emit.node("Gather",
              [in_names[0],
               emit.const("const_idx0_i64", np.asarray(0, np.int64))],
              [out_name], axis=1)


def _dec_batchnorm(op, in_names, emit, out_name):
    """BN -> the standard 5-input BatchNormalization node.  The running
    mean/var are not tape inputs (they are layer state, updated outside
    the tape) — they ride ``op.params`` (ops/batchnorm.py) and export as
    constants.  to_onnx tapes with autograd.exporting set, so the values
    here are the pre-forward running stats (the taping pass is pure)."""
    p = getattr(op, "params", {}) or {}
    from . import tensor as tensor_mod

    u = emit.uniq("bn")
    names = []
    for key in ("rm", "rv"):
        t = p[key]
        arr = tensor_mod.to_numpy(t).astype(np.float32)
        names.append(emit.const(t.name or f"{u}_{key}", arr))
    emit.node("BatchNormalization",
              [in_names[0], in_names[1], in_names[2], names[0], names[1]],
              [out_name], epsilon=float(p.get("eps", 1e-5)),
              momentum=float(p.get("momentum", 0.9)))


def _dec_reduce_mean(op, in_names, emit, out_name):
    """reduce_mean(x, axes) -> ReduceMean with axes as an input
    (opset >= 18 moved axes from attribute to input)."""
    p = getattr(op, "params", {}) or {}
    ax = p.get("ax")
    ins = [in_names[0]]
    if ax is not None:
        axes = np.asarray(list(ax), np.int64)
        ins.append(emit.const(
            f"const_axes_{'_'.join(map(str, axes.tolist()))}", axes))
    emit.node("ReduceMean", ins, [out_name],
              keepdims=1 if p.get("keepdims") else 0)


def _dec_relu6(op, in_names, emit, out_name):
    """relu6(x) -> Clip(x, 0, 6) (ONNX has no Relu6 node; opset >= 11
    carries min/max as inputs)."""
    emit.node("Clip",
              [in_names[0],
               emit.const("const_zero_f32", np.float32(0.0)),
               emit.const("const_six_f32", np.float32(6.0))], [out_name])


def _dec_mul_scalar(op, in_names, emit, out_name):
    s = float((getattr(op, "params", {}) or {}).get("s", 1.0))
    emit.node("Mul", [in_names[0], emit.const(f"const_scalar_{s!r}",
                                              np.float32(s))], [out_name])




# ONNX gate-order maps for EXPORT (ours -> ONNX): inverse of the import
# permutations (_ONNX_GATE_PERM)
_EXPORT_GATE_PERM = {"lstm": [0, 3, 1, 2], "gru": [1, 0, 2],
                     "vanilla_tanh": [0], "vanilla_relu": [0]}
_EXPORT_RNN_NODE = {"lstm": "LSTM", "gru": "GRU",
                    "vanilla_tanh": "RNN", "vanilla_relu": "RNN"}


def _dec_rnn(op, in_names, emit, out_name):
    """One taped RNN[l{l}d{d}] op (ops/rnn.py rnn_forward: a single
    layer-direction scan over the packed flat weight) -> one ONNX
    LSTM/GRU/RNN node.  The packed-weight slices ride op.params, so the
    ONNX-format W/R/B constants are computed here from the flat
    weight's concrete values (gate reorder ours->ONNX is the inverse of
    the importer's map — tests/test_sonnx round-trips both); the
    initial states are WIRED from the op's hx/cx inputs through Slice
    nodes (graph inputs and upstream-computed states export
    faithfully, nothing is baked).  The op's three outputs (y (T,B,H),
    h_T (B,H), c_T) become Squeeze views of the node's Y (T,1,B,H) /
    Y_h (1,B,H) / Y_c."""
    from .ops.rnn import _GATES

    p = getattr(op, "params", {}) or {}
    mode = p["mode"]
    H = int(p["hidden"])
    G = _GATES[mode]
    reverse = int(p["direction"]) == 1
    idx = int(p["idx"])
    sl = p["slices"]

    w_t = op.src[3][2]          # the flat packed weight Tensor
    w_flat = tensor.to_numpy(w_t)

    def unpack(name):
        a, b, shape = sl[name]
        return w_flat[a:b].reshape(shape)

    ridx = np.concatenate(
        [np.arange(q * H, (q + 1) * H)
         for q in _EXPORT_GATE_PERM[mode]])
    W = unpack("w_ih")[ridx][None]            # (1, G*H, I)
    R = unpack("w_hh")[ridx][None]            # (1, G*H, H)
    B = np.concatenate([unpack("b_ih")[ridx],
                        unpack("b_hh")[ridx]])[None]  # (1, 2*G*H)

    u = emit.uniq(_EXPORT_RNN_NODE[mode])

    def row(src_name, tag):
        # hx/cx are (L*D, B, H); the node wants row ``idx`` as (1,B,H)
        out = f"{u}_{tag}"
        emit.node("Slice",
                  [src_name,
                   emit.const(f"const_i64_{idx}",
                              np.asarray([idx], np.int64)),
                   emit.const(f"const_i64_{idx + 1}",
                              np.asarray([idx + 1], np.int64)),
                   emit.const("const_i64_0",
                              np.asarray([0], np.int64))],
                  [out])
        return out

    wn = emit.const(f"{u}_W", W.astype(np.float32))
    rn = emit.const(f"{u}_R", R.astype(np.float32))
    bn = emit.const(f"{u}_B", B.astype(np.float32))
    ins = [in_names[0], wn, rn, bn, "", row(in_names[1], "h0")]
    attrs = dict(hidden_size=H)
    if reverse:
        attrs["direction"] = "reverse"
    if mode == "gru":
        attrs["linear_before_reset"] = 1   # the cuDNN cell form
    if mode == "vanilla_relu":
        attrs["activations"] = ["Relu"]
    node_type = _EXPORT_RNN_NODE[mode]
    y_raw, h_raw = f"{u}_Y", f"{u}_Yh"
    outs = [y_raw, h_raw]
    if mode == "lstm":
        ins.append(row(in_names[2], "c0"))
        outs.append(f"{u}_Yc")
    emit.node(node_type, ins, outs, **attrs)

    # taped outputs: out0 = y (T,B,H); out1 = h_T (B,H); out2 = c_T.
    # tensor_name suffixes are deterministic (_out{i}) — derive the
    # sibling names from out0's.  Squeeze axes ride as an int64 INPUT
    # (opset >= 13 form; the exported model declares opset 20).
    assert out_name.endswith("_out0"), out_name
    stem = out_name[:-1]
    ax1 = emit.const("const_i64_axes1", np.asarray([1], np.int64))
    ax0 = emit.const("const_i64_axes0", np.asarray([0], np.int64))
    emit.node("Squeeze", [y_raw, ax1], [out_name])
    emit.node("Squeeze", [h_raw, ax0], [stem + "1"])
    if mode == "lstm":
        emit.node("Squeeze", [f"{u}_Yc", ax0], [stem + "2"])
    else:
        # rnn_forward's c_T for non-LSTM modes is zeros_like(h_T):
        # h - h gives the right shape without baking one
        emit.node("Sub", [stem + "1", stem + "1"], [stem + "2"])


_EXPORT_DECOMPOSE = {
    "Attention": _dec_attention,
    "TPAttention": _dec_attention,
    "Embedding": _dec_embedding,
    "RepeatKV": _dec_repeat_kv,
    "AttnMask": _dec_attn_mask,
    "FirstToken": _dec_first_token,
    "MulScalar": _dec_mul_scalar,
    "ReLU6": _dec_relu6,
    "ReduceMean": _dec_reduce_mean,
    "BatchNorm2d": _dec_batchnorm,
}


def to_onnx(m, inputs, model_name="singa_model"):
    """Export a Model's forward graph to an ONNX ModelProto by taping one
    forward pass over ``inputs`` (list of Tensors)."""
    prev = autograd.training
    autograd.set_training(True)
    autograd.set_exporting(True)  # taping must be pure (no BN stat writes)
    try:
        y = m.forward(*inputs)
    finally:
        autograd.set_training(prev)
        autograd.set_exporting(False)
    outputs = list(y) if isinstance(y, (list, tuple)) else [y]

    # walk the tape from outputs back to inputs/params
    params = m.get_params() if hasattr(m, "get_params") else {}
    param_by_id = {id(t.data): (name, t) for name, t in params.items()}
    input_names = {}
    for i, t in enumerate(inputs):
        input_names[id(t.data)] = f"input_{i}"

    nodes = []
    initializers = []
    seen_ops = {}
    name_ctr = [0]
    op_unames = {}

    def tensor_name(arr_id, op, idx):
        # ops created via autograd._op(_name=...) share their base name
        # across instances; qualify per op INSTANCE or value names
        # collide (e.g. every Reshape would emit "Reshape_out0")
        if id(op) not in op_unames:
            op_unames[id(op)] = f"{op.name}_{len(op_unames)}"
        return f"{op_unames[id(op)]}_out{idx}"

    exported_params = set()

    class _Emit:
        """Graph-building helper handed to _EXPORT_DECOMPOSE entries."""

        def uniq(self, base):
            name_ctr[0] += 1
            return f"{base}_{name_ctr[0]}"

        def node(self, op_type, ins, outs, **attrs):
            n = NodeProto(op_type=op_type,
                          name=f"{op_type}_{self.uniq('n')}",
                          input=list(ins), output=list(outs))
            for k, v in attrs.items():
                n.attribute.append(AttributeProto.make(k, v))
            nodes.append(n)
            return n

        def const(self, name, arr):
            """Emit a value as a Constant NODE (not an initializer):
            initializers are what backends treat as trainable weights —
            a baked causal mask or attention scale must never receive
            gradient updates.  Deduped by name, so shape-keyed names
            (const_causal_SxT, const_shape_...) are shared across the
            graph."""
            if name not in exported_params:
                exported_params.add(name)
                self.node("Constant", [], [name],
                          value=TensorProto.from_numpy(np.asarray(arr),
                                                       name))
            return name

    emit = _Emit()

    def visit(op):
        if id(op) in seen_ops:
            return
        seen_ops[id(op)] = True
        base = op.name.split("#")[0]
        is_rnn = base.startswith("RNN[l")
        in_names = []
        for src_i, (src_op, x_id, x_t, _) in enumerate(op.src):
            if is_rnn and src_i == 3:
                # the packed flat weight: _dec_rnn re-emits it as
                # unpacked ONNX W/R/B constants — resolving it here
                # would store every RNN's parameters twice (and, for a
                # re-exported imported model, drag in the importer's
                # dangling weight-packing subgraph)
                in_names.append(None)
                continue
            if x_id in input_names:
                in_names.append(input_names[x_id])
            elif x_id in param_by_id:
                pname, pt = param_by_id[x_id]
                in_names.append(pname)
                if pname not in exported_params:
                    exported_params.add(pname)
                    initializers.append(
                        TensorProto.from_numpy(tensor.to_numpy(pt), pname))
            elif src_op is not None and not isinstance(src_op, autograd.Dummy):
                visit(src_op)
                idx = src_op.y_id2idx[x_id]
                in_names.append(tensor_name(x_id, src_op, idx))
            elif x_t is not None:
                # leaf tensor that is neither a model input nor a param
                # (e.g. a constant): bake it as an initializer
                cname = f"const_{x_id}"
                in_names.append(cname)
                if cname not in exported_params:
                    exported_params.add(cname)
                    initializers.append(
                        TensorProto.from_numpy(tensor.to_numpy(x_t), cname))
            else:
                raise NotImplementedError(
                    "export found an untracked constant input (tensor with "
                    "requires_grad=False); mark it requires_grad or feed it "
                    "as a model input")
        if is_rnn:
            _dec_rnn(op, in_names, emit, tensor_name(None, op, 0))
            return
        if base in _EXPORT_DECOMPOSE:
            _EXPORT_DECOMPOSE[base](op, in_names, emit,
                                    tensor_name(None, op, 0))
            return
        onnx_type = _EXPORT_OPS.get(base)
        if onnx_type is None:
            raise NotImplementedError(
                f"export of op {base!r} not supported by sonnx frontend")
        if base == "Reshape":
            # ONNX Reshape takes the target shape as a second (int64)
            # input, not an attribute
            shape = tuple((getattr(op, "params", {}) or {}).get("shape"))
            in_names.append(emit.const(
                "const_shape_" + "_".join(str(s) for s in shape),
                np.asarray(shape, np.int64)))
        out_names = [tensor_name(None, op, i) for i in range(len(op.y_id2idx))]
        node = NodeProto(op_type=onnx_type, name=f"{base}_{name_ctr[0]}",
                         input=in_names, output=out_names)
        name_ctr[0] += 1
        # op-specific attributes (op.params carries the kwargs the op was
        # built with — see autograd._op)
        p = getattr(op, "params", {}) or {}
        if base == "SoftMax":
            node.attribute.append(AttributeProto.make("axis", p.get("axis", -1)))
        elif base == "Concat":
            # ONNX Concat has NO default axis — omitting it made
            # importers concatenate along axis 0 (caught by the UNet
            # round-trip: channel concat became batch concat).  KeyError
            # here (not a silent 0) if a Concat op ever lacks the param.
            node.attribute.append(
                AttributeProto.make("axis", int(p["axis"])))
        elif base == "Flatten":
            node.attribute.append(AttributeProto.make("axis", p.get("axis", 1)))
        elif base == "Transpose" and p.get("perm") is not None:
            node.attribute.append(AttributeProto.make("perm", list(p["perm"])))
        elif base == "Conv2d":
            node.attribute.append(AttributeProto.make(
                "strides", list(p.get("stride", (1, 1)))))
            pads = p.get("pads", ((0, 0), (0, 0)))
            # ONNX layout: all lows then all highs, any spatial rank
            node.attribute.append(AttributeProto.make(
                "pads", [pr[0] for pr in pads] + [pr[1] for pr in pads]))
            node.attribute.append(AttributeProto.make(
                "dilations", list(p.get("dilation", (1, 1)))))
            node.attribute.append(AttributeProto.make(
                "group", p.get("group", 1)))
        elif base == "ConvTranspose2d":
            node.attribute.append(AttributeProto.make(
                "strides", list(p.get("stride", (1, 1)))))
            pads = p.get("pads", ((0, 0), (0, 0)))
            node.attribute.append(AttributeProto.make(
                "pads", [pr[0] for pr in pads] + [pr[1] for pr in pads]))
            node.attribute.append(AttributeProto.make(
                "dilations", list(p.get("dilation", (1, 1)))))
            node.attribute.append(AttributeProto.make(
                "group", p.get("group", 1)))
            node.attribute.append(AttributeProto.make(
                "output_padding", list(p.get("output_padding", (0, 0)))))
        elif base in ("MaxPool2d", "AvgPool2d"):
            node.attribute.append(AttributeProto.make(
                "kernel_shape", list(p["kernel"])))
            node.attribute.append(AttributeProto.make(
                "strides", list(p.get("stride", p["kernel"]))))
            pairs = p.get("pads_pairs", ((0, 0), (0, 0)))
            node.attribute.append(AttributeProto.make(
                "pads", [pairs[0][0], pairs[1][0], pairs[0][1], pairs[1][1]]))
        elif base == "LayerNorm":
            node.attribute.append(AttributeProto.make(
                "epsilon", float(p.get("eps", 1e-5))))
            node.attribute.append(AttributeProto.make(
                "axis", int(p.get("axis", -1))))
        elif base == "Gelu":
            node.attribute.append(AttributeProto.make(
                "approximate",
                "tanh" if p.get("approximate", True) else "none"))
        elif base == "_Dropout":
            # opset >= 12: ratio is an INPUT, not an attribute
            r = float(getattr(op, "ratio", 0.5))
            node.input.append(emit.const(f"const_scalar_{r!r}",
                                         np.float32(r)))
        nodes.append(node)

    out_infos = []
    for i, out in enumerate(outputs):
        assert out.creator is not None, "export requires a taped forward"
        visit(out.creator)
        oname = tensor_name(None, out.creator,
                            out.creator.y_id2idx[id(out.data)])
        out_infos.append(ValueInfoProto(
            name=oname, elem_type=onnx_pb.FLOAT, shape=list(out.shape)))

    # visit() appends post-order (producers before consumers): already
    # topologically sorted
    in_infos = [
        ValueInfoProto(name=f"input_{i}", elem_type=onnx_pb.FLOAT,
                       shape=list(t.shape))
        for i, t in enumerate(inputs)
    ]
    in_infos += [ValueInfoProto(name=t.name, elem_type=t.data_type,
                                shape=list(t.dims))
                 for t in initializers]
    g = GraphProto(name=model_name, node=nodes, initializer=initializers,
                   input=in_infos, output=out_infos)
    # opset 20: the earliest version covering everything this frontend
    # emits (Gelu + its `approximate` attribute landed in 20; Unsqueeze
    # axes-as-input needs 13, Dropout ratio-as-input needs 12)
    m = ModelProto(graph=g)
    for o in m.opset_import:
        if not o.domain:
            o.version = 20
    return m


class SingaFrontend:
    to_onnx = staticmethod(to_onnx)


def save(model_proto: ModelProto, path: str):
    onnx_pb.save_model(model_proto, path)


def load(path: str) -> ModelProto:
    return onnx_pb.load_model(path)
