"""singa_tpu — a TPU-native deep-learning framework with the capabilities
of Apache SINGA (reference: yaochang/singa), built from scratch on
JAX/XLA/Pallas.  See SURVEY.md for the reference layer map this package
rebuilds and README.md for the design stance.
"""

from . import amp  # noqa: F401
from . import config  # noqa: F401
from .config import VERSION as __version__  # noqa: F401

# Submodules are imported lazily by user code (`from singa_tpu import
# tensor, device, autograd, layer, model, opt, sonnx`), mirroring how
# reference scripts import `from singa import ...`.
