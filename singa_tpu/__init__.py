"""singa_tpu — a TPU-native deep-learning framework with the capabilities
of Apache SINGA (reference: yaochang/singa), built from scratch on
JAX/XLA/Pallas.  See SURVEY.md for the reference layer map this package
rebuilds and README.md for the design stance.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under jax.experimental only; every
    # SPMD path here (model._build's DistOpt step, ring attention,
    # tensor/pipeline parallel) and the virtual-mesh tests call the
    # stable ``jax.shard_map`` spelling.  The experimental function
    # accepts the same (f, mesh=, in_specs=, out_specs=) call shape,
    # so alias it once at import — without this, 21 tier-1 tests fail
    # on 0.4.x with AttributeError before any singa_tpu code runs.
    # Deliberately a fill-only patch of the dependency: it installs
    # ONLY when the attribute is absent (never shadows a real
    # jax.shard_map), and both this package's call sites and the test
    # suite use the stable spelling, so a package-private helper
    # would leave the tests broken.
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, *a, **kw):
        # the stable API renamed check_rep -> check_vma
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, *a, **kw)

    _jax.shard_map = _compat_shard_map

from . import amp  # noqa: F401
from . import config  # noqa: F401
from .config import VERSION as __version__  # noqa: F401

# Submodules are imported lazily by user code (`from singa_tpu import
# tensor, device, autograd, layer, model, opt, sonnx`), mirroring how
# reference scripts import `from singa import ...`.
