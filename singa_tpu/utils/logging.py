"""Logging channels (reference: include/singa/utils/logging.h glog-style
LOG/CHECK + src/utils/channel.cc named channels teeing to file/stderr,
unverified — SURVEY.md §5.5)."""

from __future__ import annotations

import logging
import os
import sys

_channels = {}
_channel_dir = None
_stderr_default = True


def init_channel(argv0="singa_tpu", dir="", stderr=True):
    """Reference: InitChannel — set the channel output directory.

    Channels created BEFORE this call are reconfigured in place:
    their handlers are rebuilt against the new dir/stderr settings
    (previously a cached logger silently kept its stale handlers — no
    file handler, wrong stderr teeing — because ``get_channel`` only
    configures on first creation)."""
    global _channel_dir, _stderr_default
    _channel_dir = dir or None
    _stderr_default = stderr
    if _channel_dir:
        os.makedirs(_channel_dir, exist_ok=True)
    for name, logger in _channels.items():
        _configure(logger, name)


def _configure(logger, name):
    """(Re)build a channel's handlers from the current module config,
    closing any file handlers the old config opened."""
    for h in list(logger.handlers):
        logger.removeHandler(h)
        if isinstance(h, logging.FileHandler):
            h.close()
    fmt = logging.Formatter(
        "[%(asctime)s %(levelname).1s %(name)s] %(message)s", "%H:%M:%S")
    if _stderr_default:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(fmt)
        logger.addHandler(h)
    if _channel_dir:
        fh = logging.FileHandler(os.path.join(_channel_dir, f"{name}.log"))
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    if not logger.handlers:
        logger.addHandler(logging.NullHandler())


def get_channel(name="global") -> logging.Logger:
    """Named channel; logs to <dir>/<name>.log and/or stderr."""
    if name in _channels:
        return _channels[name]
    logger = logging.getLogger(f"singa_tpu.{name}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    _configure(logger, name)
    _channels[name] = logger
    return logger


# glog-style checks (reference: CHECK/CHECK_EQ/... macros)
def CHECK(cond, msg=""):
    if not cond:
        raise AssertionError(f"CHECK failed: {msg}")


def CHECK_EQ(a, b, msg=""):
    if a != b:
        raise AssertionError(f"CHECK_EQ failed: {a!r} != {b!r} {msg}")


def CHECK_GT(a, b, msg=""):
    if not a > b:
        raise AssertionError(f"CHECK_GT failed: {a!r} <= {b!r} {msg}")


def CHECK_GE(a, b, msg=""):
    if not a >= b:
        raise AssertionError(f"CHECK_GE failed: {a!r} < {b!r} {msg}")


def LOG(level="INFO", *args):
    get_channel().log(getattr(logging, level, logging.INFO),
                      " ".join(str(a) for a in args))
