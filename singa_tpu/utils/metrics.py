"""Training metrics (SURVEY.md §5.5: step timer, samples/sec/chip — the
BASELINE metric — and scaling-efficiency calculator; the reference
computes these inline in example scripts)."""

from __future__ import annotations

import time


class StepTimer:
    """Tracks per-step wall time with warmup skipping (compile steps)."""

    def __init__(self, skip_first=2):
        self.skip_first = skip_first
        self.times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        assert self._t0 is not None
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    @property
    def steady(self):
        return self.times[self.skip_first:] or self.times

    def mean_step_seconds(self) -> float:
        s = self.steady
        return sum(s) / len(s) if s else float("nan")

    def samples_per_sec(self, batch_size) -> float:
        return batch_size / self.mean_step_seconds()

    def samples_per_sec_per_chip(self, batch_size, num_chips=1) -> float:
        return self.samples_per_sec(batch_size) / num_chips


def scaling_efficiency(throughput_n_chips, throughput_1_chip, n_chips):
    """(global throughput on n chips) / (n * single-chip throughput) —
    the BASELINE.json >=90% target for DistOpt over ICI."""
    return throughput_n_chips / (n_chips * throughput_1_chip)


def accuracy(logits, labels):
    import numpy as np

    from .. import tensor

    p = tensor.to_numpy(logits) if not isinstance(logits, np.ndarray) else logits
    t = tensor.to_numpy(labels) if not isinstance(labels, np.ndarray) else labels
    return float((p.argmax(-1) == t).mean())
