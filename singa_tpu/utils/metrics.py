"""Training metrics (SURVEY.md §5.5: step timer, samples/sec/chip — the
BASELINE metric — and scaling-efficiency calculator; the reference
computes these inline in example scripts)."""

from __future__ import annotations

import collections as _collections
import time


class StepTimer:
    """Tracks per-step wall time with warmup skipping (compile steps)."""

    def __init__(self, skip_first=2):
        self.skip_first = skip_first
        self.times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        assert self._t0 is not None
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    @property
    def steady(self):
        return self.times[self.skip_first:] or self.times

    def mean_step_seconds(self) -> float:
        s = self.steady
        return sum(s) / len(s) if s else float("nan")

    def samples_per_sec(self, batch_size) -> float:
        """nan when no steps were recorded or the mean is zero (a
        zero-duration clock in tests) — never ZeroDivisionError."""
        m = self.mean_step_seconds()
        if m != m or m == 0.0:  # nan or zero mean
            return float("nan")
        return batch_size / m

    def samples_per_sec_per_chip(self, batch_size, num_chips=1) -> float:
        return self.samples_per_sec(batch_size) / num_chips


def scaling_efficiency(throughput_n_chips, throughput_1_chip, n_chips):
    """(global throughput on n chips) / (n * single-chip throughput) —
    the BASELINE.json >=90% target for DistOpt over ICI."""
    return throughput_n_chips / (n_chips * throughput_1_chip)


def percentile(values, p) -> float:
    """Nearest-rank percentile (p in [0, 100]) over a sequence.  The
    nearest-rank definition returns an OBSERVED value (p99 of 3 samples
    is the max, not an interpolation between two latencies that never
    happened), which is the convention serving dashboards use."""
    vals = sorted(values)
    if not vals:
        # empty in == nan out, matching LatencySeries.mean(); callers
        # never have to special-case "no samples yet"
        return float("nan")
    if p <= 0:
        return float(vals[0])
    import math

    rank = math.ceil(min(p, 100) / 100.0 * len(vals))
    return float(vals[min(len(vals), max(1, rank)) - 1])


#: default bound on retained raw samples per series.  ~8k float
#: samples keep RSS flat over multi-hour soaks (the previous unbounded
#: list grew linearly with uptime) while a nearest-rank p99 over the
#: retained ring still rests on ~80 tail observations.
DEFAULT_MAX_SAMPLES = 8192


class LatencySeries:
    """Accumulates per-event latencies (seconds) and summarizes them in
    the schema serving metrics report everywhere: count/mean/p50/p99/
    max.  Used by serve/stats.py for TTFT and TPOT; generic enough for
    any per-event timing.

    MEMORY BOUND: raw samples are retained in a RING of the newest
    ``max_samples`` (default :data:`DEFAULT_MAX_SAMPLES`) so a
    process-lifetime series cannot grow RSS with uptime.  The running
    ``total_sum``/``count`` pair stays EXACT over every value ever
    recorded (the Prometheus ``_sum``/``_count`` contract), and the
    observe registry's Histogram bins each value into its cumulative
    bucket ladder AT RECORD TIME (via :meth:`add_hook`), so exported
    bucket counts stay exact running totals too.  Only the summary
    percentiles/mean/max degrade once the ring wraps: they describe
    the retained window — the newest ~8k events — which is the honest
    approximation for an all-time p99 nobody can store (documented in
    docs/OBSERVABILITY.md; WINDOWED quantiles come from the observe
    timeseries rings, which carry timestamps).
    """

    def __init__(self, max_samples=DEFAULT_MAX_SAMPLES):
        if max_samples is not None and max_samples < 1:
            raise ValueError(
                f"max_samples must be >= 1 or None, got {max_samples}")
        self.max_samples = max_samples
        self.values = _collections.deque(maxlen=max_samples)
        # running totals over EVERY recorded value, maintained
        # separately from ``values`` so the bounded retained window
        # never makes the Prometheus ``_sum``/``_count`` pair
        # (export.prometheus_text) pair an all-time count with a
        # windowed sum
        self.total_sum = 0.0
        self._total_count = 0
        # record-time observers (observe.registry.Histogram bucket
        # binning, observe.timeseries window rings): called with each
        # recorded float AFTER the totals update.  A tuple, not a
        # list: the hot path's ``for h in self._hooks`` over an empty
        # tuple is the whole disabled cost.
        self._hooks = ()

    def add_hook(self, fn):
        """Register ``fn(value: float)`` to observe every future
        ``record`` (the seam the registry Histogram and the windowed
        timeseries rings attach through — adopters of a series record
        into it directly, so ``record`` is the only point that sees
        every value exactly once)."""
        self._hooks = self._hooks + (fn,)

    def remove_hook(self, fn):
        self._hooks = tuple(h for h in self._hooks if h is not fn)

    def record(self, seconds: float):
        v = float(seconds)
        self.values.append(v)
        self.total_sum += v
        self._total_count += 1
        for h in self._hooks:
            h(v)

    @property
    def count(self) -> int:
        return self._total_count

    def mean(self) -> float:
        return (sum(self.values) / len(self.values)
                if self.values else float("nan"))

    def percentile(self, p) -> float:
        return percentile(self.values, p)

    def summary(self) -> dict:
        """Stable-schema dict (tests assert the exact key set).
        ``count`` is the exact all-time total; mean/percentiles/max
        describe the retained ring (see class docstring)."""
        return {
            "count": self.count,
            "mean": self.mean(),
            "p50": self.percentile(50),
            "p99": self.percentile(99),
            "max": (max(self.values) if self.values else float("nan")),
        }


def accuracy(logits, labels):
    import numpy as np

    from .. import tensor

    p = tensor.to_numpy(logits) if not isinstance(logits, np.ndarray) else logits
    t = tensor.to_numpy(labels) if not isinstance(labels, np.ndarray) else labels
    return float((p.argmax(-1) == t).mean())
