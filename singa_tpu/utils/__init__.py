"""Utilities — the rebuild of include/singa/utils (logging channels,
timer, metrics)."""
