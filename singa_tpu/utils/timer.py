"""Timer (reference: include/singa/utils/timer.h, unverified)."""

import time


class Timer:
    """t = Timer(); ...; t.elapsed() -> seconds.  Also a context manager."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def __enter__(self):
        self.reset()
        return self

    def __exit__(self, *a):
        self.seconds = self.elapsed()
