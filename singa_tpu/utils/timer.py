"""Timer (reference: include/singa/utils/timer.h, unverified)."""

import time


class Timer:
    """t = Timer(); ...; t.elapsed() -> seconds.  Also a context manager.

    ``seconds`` is the frozen context-manager result: ``None`` until a
    ``with`` block exits (it used to not exist at all — reading it
    before exit raised AttributeError), then the block's duration; a
    re-entered timer overwrites it.  Use ``elapsed()`` for a live
    reading at any point.
    """

    def __init__(self):
        self.seconds = None
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def __enter__(self):
        self.reset()
        return self

    def __exit__(self, *a):
        self.seconds = self.elapsed()
