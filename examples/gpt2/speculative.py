"""Speculative decoding demo — greedy draft-and-verify, offline.

Trains a 2-layer tiny GPT-2 target and a 1-layer draft on the SAME
synthetic next-token data (every example in this repo is
offline-friendly; with real checkpoints you would load a big target
and a small draft instead), then decodes with
``gpt2_decode.generate_speculative``:

  * the draft proposes ``spec_k - 1`` tokens per chunk (sequential,
    cheap model);
  * the target verifies the whole chunk with ONE chunked cache
    advance — one big cache read serves spec_k positions, which is
    the speedup on a cache-read-bound decode loop;
  * every emitted token is the TARGET's greedy choice, so the output
    matches ``target.generate(prompt, temperature=0)`` (asserted
    below); the draft only sets the speed via its acceptance rate.

    python examples/gpt2/speculative.py [--steps N] [--spec-k K]
        [--new-tokens T] [--seed S]

More training steps -> the models agree on more of the learned
distribution -> higher acceptance -> more tokens per chunk.
"""

import argparse

import numpy as np

from singa_tpu import device, opt, tensor
from singa_tpu.models import gpt2_decode
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead


def train(cfg, ids, labels, steps, seed):
    if steps < 1:
        raise SystemExit("--steps must be >= 1 (untrained models have "
                         "no agreement for the draft to exploit)")
    device.get_default_device().SetRandSeed(seed)
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.AdamW(lr=1e-3, weight_decay=0.01))
    m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
    for _ in range(steps):
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
    m.eval()
    return m, float(tensor.to_numpy(loss))


def run(args):
    rng = np.random.RandomState(args.seed)
    cfg_t = GPT2Config.tiny(dropout=0.0, n_positions=256)
    cfg_d = GPT2Config.tiny(dropout=0.0, n_positions=256, n_layer=1)
    # highly learnable data (repeated motif + noise): both models pick
    # up the same loops, which is what gives the draft its acceptance
    motif = rng.randint(0, cfg_t.vocab_size, 8)
    ids = np.tile(motif, (4, 4)).astype(np.int32)[:, :32]
    noise = rng.randint(0, cfg_t.vocab_size, ids.shape)
    mask = rng.rand(*ids.shape) < 0.05
    ids[mask] = noise[mask]
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    target, lt = train(cfg_t, ids, labels, args.steps, args.seed)
    draft, ld = train(cfg_d, ids, labels, args.steps, args.seed + 1)
    print(f"trained: target loss {lt:.3f} (2 layers), "
          f"draft loss {ld:.3f} (1 layer)")

    prompt = ids[0, :12]
    ref = target.generate(prompt, max_new_tokens=args.new_tokens,
                          temperature=0)
    out, stats = gpt2_decode.generate_speculative(
        target, draft, prompt, max_new_tokens=args.new_tokens,
        spec_k=args.spec_k)
    assert (out == ref).all(), "speculative output must be target-greedy"
    if stats["chunks"]:  # max_new_tokens==1 verifies zero proposals
        detail = (f"({stats['tokens_per_chunk']:.2f} tokens/chunk, "
                  f"acceptance {stats['acceptance_rate']:.0%}) — ")
    else:
        detail = "(prefill token only, nothing verified) — "
    print(f"spec_k={args.spec_k}: {args.new_tokens} tokens in "
          f"{stats['chunks']} chunks {detail}output == target-greedy ✓")
    print("continuation:", out[len(prompt):].tolist())


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument("--new-tokens", type=int, default=48)
    p.add_argument("--seed", type=int, default=0)
    run(p.parse_args())
