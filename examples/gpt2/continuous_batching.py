"""Continuous-batching engine demo — the round-6 serving surface.

Where examples/gpt2/serve.py assembles STATIC batches (every request
waits for the slowest row in its batch), this drives
``singa_tpu.serve.InferenceEngine``: requests with ragged prompt
lengths, ragged arrival times and ragged token budgets flow through a
fixed-shape slot pool; each engine step advances every live row one
token, retires finished rows, and backfills the freed slots from the
queue in the same step.  Tokens stream per request the moment they are
emitted, and each request's stream is token-identical to its
single-prompt ``generate`` output.

    python examples/gpt2/continuous_batching.py [--model tiny|small]
        [--requests N] [--slots S] [--temperature T] [--seed S]
"""

import argparse

import numpy as np

from singa_tpu import device, tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.serve import GenerationRequest


def run(args):
    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    cfg = (GPT2Config.tiny(dropout=0.0) if args.model == "tiny"
           else GPT2Config.small(dropout=0.0, attn_impl="fused"))
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 8), np.int32), dev)],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(args.seed)
    eng = m.serve(max_slots=args.slots)
    reqs = []
    for i in range(args.requests):
        plen = int(rng.randint(4, 32))
        reqs.append(GenerationRequest(
            rng.randint(0, cfg.vocab_size, plen).astype(np.int32),
            max_new_tokens=int(rng.choice([4, 8, 12, 48])),
            temperature=args.temperature,
            seed=int(rng.randint(0, 2 ** 31 - 1)),
            on_token=lambda r, t: print(
                f"  {r.request_id}: +{t}", flush=True)
            if args.stream else None))

    # ragged arrivals: ~2 requests join per engine step
    handles, pending = [], list(reqs)
    while pending or eng.pending:
        for _ in range(int(rng.randint(0, 3))):
            if pending:
                handles.append(eng.submit(pending.pop(0)))
        eng.step()

    for h in handles:
        res = h.result()
        print(f"{res.request_id}: {len(res.tokens)} tokens, "
              f"ttft={res.ttft * 1e3:.1f}ms "
              f"tpot={(res.tpot or 0) * 1e3:.2f}ms")
    snap = eng.stats.snapshot()
    print(f"\n{snap['throughput']['tokens_per_s']:.0f} tok/s, "
          f"occupancy {snap['slots']['occupancy_mean']:.0%}, "
          f"ttft p50 {snap['latency']['ttft']['p50'] * 1e3:.1f}ms "
          f"p99 {snap['latency']['ttft']['p99'] * 1e3:.1f}ms")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "small"])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--stream", action="store_true",
                    help="print tokens as they are emitted")
    run(ap.parse_args())
