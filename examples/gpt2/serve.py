"""GPT-2 serving loop — the round-5 inference surface in one script.

The reference has no inference machinery at all (SURVEY.md §2.4 runs
full forwards); this example drives the TPU-native decode stack the way
a serving process would:

  * requests arrive as a RAGGED batch of prompts (mixed lengths) — the
    left-padding fast path decodes them lockstep in ONE compiled
    executable at the equal-length batch's throughput;
  * weights are bf16-cast and SESSION-CACHED on the model: request 2
    onward skips the per-call re-cast/re-shard entirely;
  * ``--beams K`` switches to batched beam search (every prompt's beams
    advance together, block-diagonal parent gather).

    python examples/gpt2/serve.py [--model tiny|small] [--requests N]
        [--batch B] [--new-tokens T] [--beams K] [--top-p P] [--seed S]
"""

import argparse
import time

import numpy as np

from singa_tpu import device, tensor
from singa_tpu.models import gpt2_decode
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead


def make_requests(rng, cfg, batch):
    """A ragged batch: prompt lengths drawn from [8, 64)."""
    lens = rng.randint(8, 64, size=batch)
    return [rng.randint(0, cfg.vocab_size, n).astype(np.int32)
            for n in lens]


def run(args):
    import jax.numpy as jnp

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    kw = {}
    if args.kv_heads:  # GQA: n_head/kv_heads x smaller decode cache
        kw["n_kv_head"] = args.kv_heads
    cfg = (GPT2Config.tiny(dropout=0.0, **kw) if args.model == "tiny"
           else GPT2Config.small(dropout=0.0, attn_impl="fused", **kw))
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 8), np.int32), dev)],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(args.seed)
    dts = []
    for req in range(args.requests):
        prompts = make_requests(rng, cfg, args.batch)
        t0 = time.time()
        cache_dtype = "int8" if args.cache_int8 else None
        if args.beams > 1:
            outs = gpt2_decode.generate_beam(
                m, prompts, max_new_tokens=args.new_tokens,
                num_beams=args.beams, dtype=jnp.bfloat16,
                cache_dtype=cache_dtype)
        else:
            outs = gpt2_decode.generate(
                m, prompts, max_new_tokens=args.new_tokens,
                temperature=args.temperature, top_p=args.top_p,
                rng=rng, dtype=jnp.bfloat16, cache_dtype=cache_dtype)
        dt = time.time() - t0
        dts.append(dt)
        for p, o in zip(prompts, outs):
            assert len(o) == len(p) + args.new_tokens
            assert o[:len(p)].tolist() == p.tolist()
        lens = [len(p) for p in prompts]
        print(f"request {req}: batch={args.batch} "
              f"prompt_lens={min(lens)}..{max(lens)} "
              f"+{args.new_tokens} tok/row in {dt:.3f}s"
              + ("  (compile+cache warm)" if req == 0 else ""))
    # request 0 pays compile + the weight cast (cached after); steady
    # state is everything after it
    if len(dts) > 1:
        warm = sum(dts[1:])
        toks = args.batch * args.new_tokens * (len(dts) - 1)
        print(f"steady-state: {toks / warm:.1f} tokens/sec over "
              f"{len(dts) - 1} warm requests "
              f"(request 0 took {dts[0]:.2f}s)")
    return 0


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=["tiny", "small"], default="tiny")
    p.add_argument("--requests", type=int, default=3)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--new-tokens", type=int, default=16)
    p.add_argument("--beams", type=int, default=1)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="GQA: number of K/V heads (0 = full MHA); "
                        "must divide the model's n_head")
    p.add_argument("--cache-int8", action="store_true",
                   help="quantize the KV cache to int8 (~2x less "
                        "cache traffic; argmax near-ties may flip)")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    raise SystemExit(run(p.parse_args()))
