"""GPT-2 training over a five-axis mesh — the model-parallel showcase
(the reference has no model parallelism at all, SURVEY.md §2.3; its
closest entry point is examples/cnn/train_mpi.py's data-parallel
launch, unverified).

One definition serves every layout: pick axis sizes, get Megatron
tensor parallelism (tp), ring-attention sequence parallelism (sp),
GShard MoE expert parallelism (--moe-every + ep), all composed with
data parallelism (dp) — XLA's SPMD partitioner inserts the
collectives.  Self-provisions a virtual CPU mesh on a 1-chip box.

    python examples/gpt2/train_parallel.py --dp 2 --tp 2 --sp 2 \\
        --force-cpu-devices 8 --steps 10
    python examples/gpt2/train_parallel.py --dp 2 --ep 4 --moe-every 1 \\
        --force-cpu-devices 8
"""

import argparse
import time

import numpy as np


def run(args):
    if args.force_cpu_devices:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.force_cpu_devices}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from singa_tpu import device, opt, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.parallel import sharding as shd

    if args.coordinator:
        from singa_tpu.parallel.communicator import initialize_distributed

        initialize_distributed(args.coordinator, args.num_processes,
                               args.process_id)

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)

    world = args.dp * args.tp * args.sp * args.ep
    mesh = shd.create_mesh(dp=args.dp, tp=args.tp, sp=args.sp, ep=args.ep)
    plan = shd.ShardingPlan(mesh)
    print(f"mesh: dp={args.dp} tp={args.tp} sp={args.sp} ep={args.ep} "
          f"({world} devices, backend={jax.devices()[0].platform})")

    cfg = (GPT2Config.tiny(dropout=args.dropout,
                           moe_every=args.moe_every,
                           moe_experts=args.ep if args.moe_every else 8)
           if args.size == "tiny"
           else getattr(GPT2Config, args.size)(
               dropout=args.dropout, moe_every=args.moe_every))
    m = GPT2LMHead(cfg, plan=plan)
    m.set_sharding_plan(plan)
    m.set_optimizer(opt.Adam(lr=args.lr))

    rng = np.random.RandomState(args.seed)
    b, s = args.batch_size, args.seq_length
    if b % args.dp or s % args.sp:
        raise SystemExit(f"batch {b} %% dp or seq {s} %% sp != 0")
    ids0 = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32))
    m.compile([ids0], is_train=True, use_graph=True)

    t_hist = []
    for step in range(args.steps):
        raw = rng.randint(0, cfg.vocab_size, (b, s + 1))
        x = tensor.from_numpy(raw[:, :-1].astype(np.int32))
        y = tensor.from_numpy(raw[:, 1:].astype(np.int32))
        t0 = time.time()
        _, loss = m(x, y)
        lv = float(tensor.to_numpy(loss))
        dt = time.time() - t0
        t_hist.append(dt)
        if step % max(1, args.steps // 10) == 0 or step == args.steps - 1:
            print(f"step {step}: loss={lv:.4f} {dt * 1e3:.1f}ms")
    steady = t_hist[2:] or t_hist
    print(f"throughput: {b / (sum(steady) / len(steady)):.1f} samples/s "
          f"(global batch {b}, seq {s}, {world} devices)")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size", choices=["tiny", "small", "medium"],
                   default="tiny")
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--moe-every", type=int, default=None)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-length", type=int, default=32)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--force-cpu-devices", type=int, default=None)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    args = p.parse_args()
    run(args)
