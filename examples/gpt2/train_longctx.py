"""Long-context GPT-2 training — the single-chip long-sequence recipe
(SURVEY.md §5.7; the reference has no long-context story at all).

Two levers compose:
  * ``--attn flash``: Pallas online-softmax attention — HBM O(S·D)
    instead of the fused path's O(S²) score matrices (which OOM first
    as S grows; LONGCTX.json records the measured crossover on v5e);
  * ``--remat``: ``jax.checkpoint`` on the attention/MLP bodies —
    recompute instead of storing residuals.

For sequences beyond one chip's HBM, switch to ring attention over a
``seq`` mesh axis (examples/gpt2/train_parallel.py --sp).

    python examples/gpt2/train_longctx.py --seqlen 2048 --attn flash \\
        --remat --steps 5
"""

import argparse
import time

import numpy as np


def run(args):
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    if args.bf16:
        amp.enable(True)
    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    cfg = GPT2Config(
        vocab_size=args.vocab, n_positions=args.seqlen,
        n_embd=args.embd, n_layer=args.layers,
        n_head=args.heads, dropout=0.0,
        attn_impl=args.attn, remat=args.remat)
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=args.lr))

    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, cfg.vocab_size,
                         (args.batch, args.seqlen)).astype(np.int32)
    labels_np = np.roll(ids_np, -1, axis=1).astype(np.int32)
    ids = tensor.from_numpy(ids_np, dev)
    labels = tensor.from_numpy(labels_np, dev)
    m.compile([ids], is_train=True, use_graph=True)

    tokens = args.batch * args.seqlen
    for step in range(args.steps):
        t0 = time.time()
        _, loss = m(ids, labels)
        lv = float(tensor.to_numpy(loss))
        dt = time.time() - t0
        print(f"step {step}: loss={lv:.4f} "
              f"({tokens / dt:,.0f} tokens/s{' incl. compile' if step == 0 else ''})")
    stats = dev.jax_device.memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    if peak:
        print(f"peak HBM: {peak / 2**30:.2f} GiB")
    assert np.isfinite(lv)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqlen", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--embd", type=int, default=512)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--attn", choices=["fused", "flash"], default="flash")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--steps", type=int, default=5)
    run(ap.parse_args())
