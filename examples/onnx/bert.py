"""BERT via sonnx (reference: examples/onnx/bert.py imports a pretrained
ONNX BERT-base, unverified — config #4).  No network in this container,
so by default this script builds the native BERT, round-trips the MLM
head through ONNX export+import to exercise sonnx, then trains masked-LM
on synthetic batches.  Pass --onnx-model to import a real checkpoint.

    python examples/onnx/bert.py --size tiny --steps 10
    python examples/onnx/bert.py --onnx-model bert.onnx
"""

import argparse
import time

import numpy as np


from singa_tpu import device, opt, sonnx, tensor  # noqa: E402
from singa_tpu.models.bert import BertConfig, BertForMaskedLM  # noqa: E402


def mask_tokens(ids, vocab_size, rng, mask_id=103, p=0.15):
    """BERT MLM masking: 15% positions, 80/10/10 mask/random/keep."""
    labels = ids.copy()
    masked = rng.rand(*ids.shape) < p
    coin = rng.rand(*ids.shape)
    inp = ids.copy()
    inp[masked & (coin < 0.8)] = mask_id
    rand = masked & (coin >= 0.8) & (coin < 0.9)
    inp[rand] = rng.randint(0, vocab_size, rand.sum())
    return inp, labels


def run(args):
    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    rng = np.random.RandomState(args.seed)

    if args.onnx_model:
        print(f"importing {args.onnx_model} via sonnx")
        rep = sonnx.prepare(args.onnx_model, dev)
        ids = rng.randint(0, 30522, (args.batch_size, args.seq_length))
        outs = rep.run([ids.astype(np.int64)])
        print("imported model outputs:",
              [tuple(o.shape) for o in outs])
        return

    cfg = BertConfig.tiny() if args.size == "tiny" else BertConfig.base()
    m = BertForMaskedLM(cfg)
    sgd = opt.Adam(lr=args.lr)
    m.set_optimizer(sgd)

    ids0 = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size,
                    (args.batch_size, args.seq_length)).astype(np.int32), dev)
    m.compile([ids0], is_train=True, use_graph=args.use_graph)

    t_hist = []
    for step in range(args.steps):
        raw = rng.randint(0, cfg.vocab_size,
                          (args.batch_size, args.seq_length))
        inp, labels = mask_tokens(raw, cfg.vocab_size, rng)
        x = tensor.from_numpy(inp.astype(np.int32), dev)
        y = tensor.from_numpy(labels.astype(np.int32), dev)
        t0 = time.time()
        _, loss = m(x, y)
        loss_v = float(loss.data)
        dt = time.time() - t0
        t_hist.append(dt)
        if step % max(1, args.steps // 10) == 0 or step == args.steps - 1:
            print(f"step {step}: loss={loss_v:.4f} {dt * 1e3:.1f}ms")
    steady = t_hist[2:] or t_hist
    sps = args.batch_size / (sum(steady) / len(steady))
    print(f"throughput: {sps:.1f} samples/s/chip "
          f"(batch {args.batch_size}, seq {args.seq_length})")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size", choices=["tiny", "base"], default="tiny")
    p.add_argument("--onnx-model", default=None)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-length", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--use-graph", action="store_true", default=True)
    p.add_argument("--no-graph", dest="use_graph", action="store_false")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    run(args)
