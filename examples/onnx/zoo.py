"""ONNX model zoo round-trips (reference: examples/onnx/{mobilenet,
vgg16,vgg19,arcface,fer_emotion,...}.py each download a pretrained ONNX
zoo checkpoint and run it through sonnx, unverified).

This container has no network, so the same sonnx machinery is exercised
offline: each zoo architecture is built natively, exported with
``sonnx.to_onnx``, re-imported with ``sonnx.prepare`` — the code path a
downloaded checkpoint takes — and checked for output parity, then the
imported graph is trained for a step via ``SONNXModel`` to show imports
stay differentiable.

    python examples/onnx/zoo.py                 # all models
    python examples/onnx/zoo.py --model vgg11   # one model
"""

import argparse
import time

import numpy as np

from singa_tpu import device, layer, opt, sonnx, tensor


def _zoo():
    from singa_tpu.models.alexnet import AlexNet
    from singa_tpu.models.mobilenet import mobilenet_v2
    from singa_tpu.models.resnet import resnet18, resnet50
    from singa_tpu.models.unet import unet
    from singa_tpu.models.vgg import vgg11, vgg16
    from singa_tpu.models.xceptionnet import Xception

    # (factory, input hw, classifier_train) — small widths keep the
    # offline demo quick; classifier_train=False marks models whose
    # labels are not 1-of-10 (the registry carries it so the runner
    # needs no per-name special cases)
    return {
        "mobilenet_v2": (lambda: mobilenet_v2(num_classes=10,
                                              width_mult=0.5), 64, True),
        "vgg11": (lambda: vgg11(num_classes=10, batch_norm=True,
                                hidden=256), 64, True),
        "vgg16": (lambda: vgg16(num_classes=10, hidden=256), 64, True),
        "resnet18": (lambda: resnet18(num_classes=10), 64, True),
        "resnet50": (lambda: resnet50(num_classes=10), 64, True),
        "alexnet": (lambda: AlexNet(num_classes=10), 224, True),
        "xception": (lambda: Xception(num_classes=10), 96, True),
        # segmentation family: ConvTranspose decoder + skip concats
        # (round-4 importer/exporter coverage); per-pixel labels, so no
        # classifier-style imported-graph training
        "unet": (lambda: unet(num_classes=4, base_channels=8,
                              depth=2), 64, False),
    }


def run_one(name, dev, batch, seed, train_steps):
    factory, hw, classifier_train = _zoo()[name]
    rng = np.random.RandomState(seed)
    m = factory()
    x = tensor.from_numpy(
        rng.randn(batch, 3, hw, hw).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    t0 = time.time()
    native = tensor.to_numpy(m.forward(x))
    proto = sonnx.to_onnx(m, [x])
    rep = sonnx.prepare(proto, dev)
    (out,) = rep.run([x])
    err = float(np.max(np.abs(tensor.to_numpy(out) - native)))
    ok = err < 1e-2
    print(f"{name}: roundtrip max|Δ|={err:.2e} "
          f"({'OK' if ok else 'MISMATCH'}), "
          f"{len(proto.graph.node)} nodes, {time.time() - t0:.1f}s")

    if train_steps and classifier_train:
        class Trainable(sonnx.SONNXModel):
            def train_one_batch(self, x, y):
                out = self.forward(x)
                loss = self.loss_fn(out, y)
                self.optimizer(loss)
                return out, loss

        tm = Trainable(proto, dev)  # default device is CppCPU (host)
        tm.loss_fn = layer.SoftMaxCrossEntropy()
        tm.set_optimizer(opt.SGD(lr=1e-3, momentum=0.9))
        y = tensor.from_numpy(
            rng.randint(0, 10, (batch,)).astype(np.int32), dev)
        # graph mode: the imported graph's whole train step compiles to
        # ONE executable — eager per-node dispatch of a 200-node import
        # is dominated by host->device latency
        tm.compile([x], is_train=True, use_graph=True)
        losses = [float(tm(x, y)[1].data) for _ in range(train_steps)]
        print(f"{name}: imported-graph training loss "
              f"{losses[0]:.4f} -> {losses[-1]:.4f}")
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="all",
                    choices=["all"] + sorted(_zoo()))
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--train-steps", type=int, default=3)
    args = ap.parse_args()

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    names = sorted(_zoo()) if args.model == "all" else [args.model]
    results = {n: run_one(n, dev, args.batch, args.seed, args.train_steps)
               for n in names}
    assert all(results.values()), results


if __name__ == "__main__":
    main()
