"""GPT-2 via sonnx (reference: examples/onnx/gpt2.py imports a pretrained
ONNX GPT-2, unverified — SURVEY.md §2.4's ONNX model zoo).  No network in
this container, so by default this script builds the native GPT-2,
round-trips it through ONNX export+import (decomposed causal attention,
tied lm_head), checks the imported logits match, then trains causal-LM
on synthetic batches and samples a continuation.  Pass --onnx-model to
import a real checkpoint instead.

    python examples/onnx/gpt2.py --size tiny --steps 10
    python examples/onnx/gpt2.py --onnx-model gpt2.onnx
"""

import argparse
import time

import numpy as np

from singa_tpu import device, opt, sonnx, tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead


def run(args):
    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    rng = np.random.RandomState(args.seed)

    if args.onnx_model:
        print(f"importing {args.onnx_model} via sonnx")
        rep = sonnx.prepare(args.onnx_model, dev)
        ids = rng.randint(0, 50257, (args.batch_size, args.seq_length))
        outs = rep.run([ids.astype(np.int64)])
        print("imported model outputs:", [tuple(o.shape) for o in outs])
        return

    cfg = (GPT2Config.tiny(dropout=0.0) if args.size == "tiny"
           else getattr(GPT2Config, args.size)())
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=args.lr))

    ids0 = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size,
                    (args.batch_size, args.seq_length)).astype(np.int32),
        dev)
    m.compile([ids0], is_train=True, use_graph=args.use_graph)

    # -- ONNX roundtrip: exported graph must reproduce native logits ----
    m.eval()
    native = tensor.to_numpy(m.forward(ids0))
    rep = sonnx.prepare(sonnx.to_onnx(m, [ids0]), dev)
    imported = tensor.to_numpy(rep.run([tensor.to_numpy(ids0)])[0])
    err = float(np.abs(native - imported).max())
    print(f"onnx roundtrip: max |native - imported| = {err:.2e}")
    assert err < 1e-3, "ONNX roundtrip diverged"
    m.train(True)

    # -- synthetic causal-LM training -----------------------------------
    t_hist = []
    for step in range(args.steps):
        raw = rng.randint(0, cfg.vocab_size,
                          (args.batch_size, args.seq_length + 1))
        x = tensor.from_numpy(raw[:, :-1].astype(np.int32), dev)
        y = tensor.from_numpy(raw[:, 1:].astype(np.int32), dev)
        t0 = time.time()
        _, loss = m(x, y)
        loss_v = float(loss.data)
        dt = time.time() - t0
        t_hist.append(dt)
        if step % max(1, args.steps // 10) == 0 or step == args.steps - 1:
            print(f"step {step}: loss={loss_v:.4f} {dt * 1e3:.1f}ms")
    steady = t_hist[2:] or t_hist
    sps = args.batch_size / (sum(steady) / len(steady))
    print(f"throughput: {sps:.1f} samples/s/chip "
          f"(batch {args.batch_size}, seq {args.seq_length})")

    out = m.generate(np.arange(8) % cfg.vocab_size,
                     max_new_tokens=args.gen_tokens, temperature=0.8,
                     rng=rng)
    print(f"sampled continuation ({args.gen_tokens} new tokens):",
          out.tolist())


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size", choices=["tiny", "small", "medium"],
                   default="tiny")
    p.add_argument("--onnx-model", default=None)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-length", type=int, default=64)
    p.add_argument("--gen-tokens", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--use-graph", action="store_true", default=True)
    p.add_argument("--no-graph", dest="use_graph", action="store_false")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    run(args)
