"""MLP training example (reference: examples/mlp/, unverified — config #1
in BASELINE.json).  Trains a 2-layer MLP on a synthetic two-moon-style
dataset, exactly mirroring the reference script's flow:

    python examples/mlp/train.py [--use-graph] [--epochs N] [--device tpu|cpu]

``--steps-per-dispatch K`` (requires --use-graph) runs each epoch
through ``Model.train_n_batches``: the epoch's batches are stacked with
a leading K axis and all K optimizer steps execute in ONE compiled
``lax.scan`` dispatch — the round-5 cure for per-step host round-trip
latency (identical math; the loss history comes back as a (K,) array).
"""

import argparse
import time

import numpy as np


from singa_tpu import device, opt, tensor  # noqa: E402
from singa_tpu.models.mlp import MLP  # noqa: E402


def load_data(n=400, seed=0):
    """Synthetic separable data (reference uses a generated 2-D dataset)."""
    rng = np.random.RandomState(seed)
    # two gaussian blobs, 2 classes
    x0 = rng.randn(n // 2, 2).astype(np.float32) + np.array([2, 2], np.float32)
    x1 = rng.randn(n // 2, 2).astype(np.float32) + np.array([-2, -2], np.float32)
    x = np.concatenate([x0, x1])
    y = np.concatenate([np.zeros(n // 2), np.ones(n // 2)]).astype(np.int32)
    idx = rng.permutation(n)
    return x[idx], y[idx]


def accuracy(pred, target):
    return float((pred.argmax(-1) == target).mean())


def run(args):
    dev = device.create_tpu_device(0) if args.device == "tpu" else \
        device.get_default_device()
    dev.SetRandSeed(args.seed)

    x_np, y_np = load_data()
    n_train = int(0.8 * len(x_np))
    batch = args.batch_size

    m = MLP(data_size=2, perceptron_size=3, num_classes=2)
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5)
    m.set_optimizer(sgd)

    tx = tensor.Tensor((batch, 2), dev)
    m.compile([tx], is_train=True, use_graph=args.use_graph, sequential=False)

    if batch > n_train:
        raise SystemExit(
            f"batch size {batch} exceeds training set size {n_train}")

    multi = args.steps_per_dispatch > 1
    if multi and not args.use_graph:
        raise SystemExit("--steps-per-dispatch requires --use-graph")
    for epoch in range(args.epochs):
        t0 = time.time()
        tot_loss, correct, seen = 0.0, 0, 0
        starts = list(range(0, n_train - batch + 1, batch))
        tail = []
        if multi:
            # one dispatch per K batches: stack a leading steps axis;
            # the epoch's remainder (fewer than K batches) runs through
            # the single-step path below so NO batch is dropped
            k = args.steps_per_dispatch
            n_full = (len(starts) // k) * k
            for j in range(0, n_full, k):
                sl = starts[j:j + k]
                xs = np.stack([x_np[i:i + batch] for i in sl])
                ys = np.stack([y_np[i:i + batch] for i in sl])
                outs, losses = m.train_n_batches(
                    tensor.from_numpy(xs, dev), tensor.from_numpy(ys, dev))
                tot_loss += float(np.asarray(losses.data).sum())
                pred = np.asarray(outs.data).argmax(-1)
                correct += int((pred == ys).sum())
                seen += batch * k
            tail = starts[n_full:]
        if not multi or tail:
            for i in (starts if not multi else tail):
                xb = tensor.from_numpy(x_np[i:i + batch], dev)
                yb = tensor.from_numpy(y_np[i:i + batch], dev)
                out, loss = m(xb, yb)
                tot_loss += float(loss.data)
                correct += int((tensor.to_numpy(out).argmax(-1)
                                == y_np[i:i + batch]).sum())
                seen += batch
        print(f"epoch {epoch}: loss={tot_loss / max(1, seen // batch):.4f} "
              f"acc={correct / seen:.4f} time={time.time() - t0:.3f}s")

    # eval
    m.eval()
    xe = tensor.from_numpy(x_np[n_train:], dev)
    out = m(xe)
    acc = accuracy(tensor.to_numpy(out), y_np[n_train:])
    print(f"eval accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--use-graph", action="store_true", default=False)
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="K>1: run K steps per compiled dispatch "
                        "(train_n_batches; requires --use-graph)")
    p.add_argument("--device", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    acc = run(args)
    assert acc > 0.9, f"MLP failed to learn (acc={acc})"
