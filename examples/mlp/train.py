"""MLP training example (reference: examples/mlp/, unverified — config #1
in BASELINE.json).  Trains a 2-layer MLP on a synthetic two-moon-style
dataset, exactly mirroring the reference script's flow:

    python examples/mlp/train.py [--use-graph] [--epochs N] [--device tpu|cpu]
"""

import argparse
import time

import numpy as np


from singa_tpu import device, opt, tensor  # noqa: E402
from singa_tpu.models.mlp import MLP  # noqa: E402


def load_data(n=400, seed=0):
    """Synthetic separable data (reference uses a generated 2-D dataset)."""
    rng = np.random.RandomState(seed)
    # two gaussian blobs, 2 classes
    x0 = rng.randn(n // 2, 2).astype(np.float32) + np.array([2, 2], np.float32)
    x1 = rng.randn(n // 2, 2).astype(np.float32) + np.array([-2, -2], np.float32)
    x = np.concatenate([x0, x1])
    y = np.concatenate([np.zeros(n // 2), np.ones(n // 2)]).astype(np.int32)
    idx = rng.permutation(n)
    return x[idx], y[idx]


def accuracy(pred, target):
    return float((pred.argmax(-1) == target).mean())


def run(args):
    dev = device.create_tpu_device(0) if args.device == "tpu" else \
        device.get_default_device()
    dev.SetRandSeed(args.seed)

    x_np, y_np = load_data()
    n_train = int(0.8 * len(x_np))
    batch = args.batch_size

    m = MLP(data_size=2, perceptron_size=3, num_classes=2)
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5)
    m.set_optimizer(sgd)

    tx = tensor.Tensor((batch, 2), dev)
    m.compile([tx], is_train=True, use_graph=args.use_graph, sequential=False)

    if batch > n_train:
        raise SystemExit(
            f"batch size {batch} exceeds training set size {n_train}")

    for epoch in range(args.epochs):
        t0 = time.time()
        tot_loss, correct, seen = 0.0, 0, 0
        for i in range(0, n_train - batch + 1, batch):
            xb = tensor.from_numpy(x_np[i:i + batch], dev)
            yb = tensor.from_numpy(y_np[i:i + batch], dev)
            out, loss = m(xb, yb)
            tot_loss += float(loss.data)
            correct += int((tensor.to_numpy(out).argmax(-1) == y_np[i:i + batch]).sum())
            seen += batch
        print(f"epoch {epoch}: loss={tot_loss / max(1, seen // batch):.4f} "
              f"acc={correct / seen:.4f} time={time.time() - t0:.3f}s")

    # eval
    m.eval()
    xe = tensor.from_numpy(x_np[n_train:], dev)
    out = m(xe)
    acc = accuracy(tensor.to_numpy(out), y_np[n_train:])
    print(f"eval accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--use-graph", action="store_true", default=False)
    p.add_argument("--device", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    acc = run(args)
    assert acc > 0.9, f"MLP failed to learn (acc={acc})"
