"""Dataset providers for the CNN examples (reference:
examples/cnn/data/{mnist,cifar10,cifar100}.py, unverified — those download
real datasets; this container has no network, so data is synthesized with
the real datasets' shapes/statistics, which is what the reference's own
benchmark.py does for throughput runs)."""

import numpy as np

_SPECS = {
    "mnist": dict(channels=1, size=28, classes=10),
    "cifar10": dict(channels=3, size=32, classes=10),
    "cifar100": dict(channels=3, size=32, classes=100),
    "imagenet": dict(channels=3, size=224, classes=1000),
}


def load(name, n_train=512, n_val=128, seed=0):
    spec = _SPECS[name]
    rng = np.random.RandomState(seed)
    c, s, k = spec["channels"], spec["size"], spec["classes"]

    def gen(n):
        # class-dependent mean shift so models can actually learn
        y = rng.randint(0, k, (n,)).astype(np.int32)
        x = rng.randn(n, c, s, s).astype(np.float32) * 0.5
        shift = (y.astype(np.float32) / k - 0.5)[:, None, None, None]
        x += shift
        return x, y

    x_tr, y_tr = gen(n_train)
    x_va, y_va = gen(n_val)
    return (x_tr, y_tr), (x_va, y_va), spec
