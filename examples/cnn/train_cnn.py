"""CNN training entry (reference: examples/cnn/train_cnn.py, unverified):

    python examples/cnn/train_cnn.py cnn mnist --use-graph
    python examples/cnn/train_cnn.py resnet18 cifar10 --epochs 2

``--binfile DIR`` routes the training data through the on-disk BinFile
record store + threaded prefetching DataLoader (native C++ MPMC queue
when built, pure-Python fallback otherwise) instead of in-memory numpy —
the reference's reader->decoder->safe_queue pipeline, end to end.
"""

import argparse
import time

import numpy as np


from singa_tpu import device, opt, tensor  # noqa: E402
import data as data_mod  # noqa: E402


def create_model(name, num_classes, num_channels):
    if name == "cnn":
        from singa_tpu.models.cnn import CNN
        return CNN(num_classes=num_classes, num_channels=num_channels)
    if name == "alexnet":
        from singa_tpu.models.alexnet import AlexNet
        return AlexNet(num_classes=num_classes, num_channels=num_channels)
    if name == "xceptionnet":
        from singa_tpu.models.xceptionnet import Xception
        return Xception(num_classes=num_classes, num_channels=num_channels)
    if name.startswith("resnet"):
        from singa_tpu.models import resnet
        return resnet.create_model(name, num_classes=num_classes)
    raise ValueError(f"unknown model {name}")


def run(args):
    dev = device.create_tpu_device(0) if args.device == "tpu" else \
        device.get_default_device()
    dev.SetRandSeed(args.seed)

    (x_tr, y_tr), (x_va, y_va), spec = data_mod.load(
        args.data, n_train=args.n_train, n_val=args.n_val, seed=args.seed)
    batch = args.batch_size
    n_train = (len(x_tr) // batch) * batch
    if n_train == 0:
        raise SystemExit(f"batch size {batch} exceeds dataset size {len(x_tr)}")

    m = create_model(args.model, spec["classes"], spec["channels"])
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5)
    m.set_optimizer(sgd)
    tx = tensor.Tensor((batch, spec["channels"], spec["size"], spec["size"]), dev)
    m.compile([tx], is_train=True, use_graph=args.use_graph, sequential=False)

    loader = None
    if args.binfile:
        import os

        from singa_tpu.io import loader as loader_mod

        os.makedirs(args.binfile, exist_ok=True)
        path = os.path.join(args.binfile, f"{args.data}_train.bin")
        if not os.path.exists(path):
            loader_mod.write_dataset(path, x_tr[:n_train], y_tr[:n_train])
            print(f"wrote BinFile dataset: {path}")
        loader = loader_mod.DataLoader(path, batch_size=batch, shuffle=True,
                                       num_workers=2, seed=args.seed)

    for epoch in range(args.epochs):
        m.train()
        t0 = time.time()
        tot_loss, correct, seen = 0.0, 0, 0
        if loader is not None:
            batches = ((xb_np, yb_np) for xb_np, yb_np in loader)
        else:
            batches = ((x_tr[i:i + batch], y_tr[i:i + batch])
                       for i in range(0, n_train, batch))
        for xb_np, yb_np in batches:
            xb = tensor.from_numpy(np.ascontiguousarray(xb_np), dev)
            yb = tensor.from_numpy(np.ascontiguousarray(yb_np), dev)
            out, loss = m(xb, yb)
            tot_loss += float(loss.data)
            correct += int((tensor.to_numpy(out).argmax(-1) == yb_np).sum())
            seen += batch
        dt = time.time() - t0
        print(f"epoch {epoch}: loss={tot_loss / (seen // batch):.4f} "
              f"acc={correct / seen:.4f} time={dt:.2f}s "
              f"({seen / dt:.1f} samples/s)")

    m.eval()
    correct = 0
    for i in range(0, len(x_va) - batch + 1, batch):
        xb = tensor.from_numpy(x_va[i:i + batch], dev)
        out = m(xb)
        correct += int((tensor.to_numpy(out).argmax(-1) == y_va[i:i + batch]).sum())
    n_eval = (len(x_va) // batch) * batch
    if n_eval:
        print(f"eval accuracy: {correct / n_eval:.4f}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("model", nargs="?", default="cnn",
                   choices=["cnn", "alexnet", "resnet18", "resnet34",
                            "resnet50", "resnet101", "resnet152",
                            "xceptionnet"])
    p.add_argument("data", nargs="?", default="mnist",
                   choices=["mnist", "cifar10", "cifar100", "imagenet"])
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.005)
    p.add_argument("--use-graph", action="store_true", default=False)
    p.add_argument("--device", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--n-train", type=int, default=512)
    p.add_argument("--n-val", type=int, default=128)
    p.add_argument("--binfile", metavar="DIR", default=None,
                   help="write/read training data through a BinFile "
                        "record store + prefetching DataLoader")
    args = p.parse_args()
    run(args)
