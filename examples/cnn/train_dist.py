"""Distributed data-parallel CNN training (reference:
examples/cnn/train_mpi.py + train_multiprocess.py, unverified — the
DistOpt/NCCL entry points; config #5 workload).

In the TPU-native stack there is no mpiexec: a single controller drives
every chip in the mesh (multi-host via --coordinator, the
jax.distributed control plane).  All five reference sync modes:

    python examples/cnn/train_dist.py resnet18 cifar10 --dist-option plain
    python examples/cnn/train_dist.py cnn mnist --dist-option sparseTopK --spars 0.05
"""

import argparse
import time

import numpy as np



def run(args):
    if args.force_cpu_devices:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.force_cpu_devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from singa_tpu import device, opt, tensor
    from singa_tpu.parallel.dist_opt import DistOpt
    import data as data_mod
    from train_cnn import create_model

    if args.coordinator:
        from singa_tpu.parallel.communicator import initialize_distributed

        initialize_distributed(args.coordinator, args.num_processes,
                               args.process_id)

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)

    (x_tr, y_tr), _, spec = data_mod.load(args.data, n_train=args.n_train,
                                          seed=args.seed)
    batch = args.batch_size

    m = create_model(args.model, spec["classes"], spec["channels"])
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5)
    dist_opt = DistOpt(sgd, num_devices=args.num_devices)
    m.set_optimizer(dist_opt)
    print(f"world size: {dist_opt.world_size} "
          f"(devices: {len(jax.devices())}, dist_option={args.dist_option})")
    if batch % dist_opt.world_size:
        raise SystemExit(f"batch {batch} % world {dist_opt.world_size} != 0")

    tx = tensor.Tensor((batch, spec["channels"], spec["size"], spec["size"]),
                       dev)
    m.compile([tx], is_train=True, use_graph=True, sequential=False)

    n_train = (len(x_tr) // batch) * batch
    for epoch in range(args.epochs):
        t0 = time.time()
        tot, seen, correct = 0.0, 0, 0
        for i in range(0, n_train, batch):
            xb = tensor.from_numpy(x_tr[i:i + batch], dev)
            yb = tensor.from_numpy(y_tr[i:i + batch], dev)
            out, loss = m(xb, yb, dist_option=args.dist_option,
                          spars=args.spars)
            tot += float(loss.data)
            correct += int((tensor.to_numpy(out).argmax(-1) == y_tr[i:i + batch]).sum())
            seen += batch
        dt = time.time() - t0
        print(f"epoch {epoch}: loss={tot / (seen // batch):.4f} "
              f"acc={correct / seen:.4f} time={dt:.2f}s "
              f"({seen / dt:.1f} samples/s global, "
              f"{seen / dt / dist_opt.world_size:.1f}/chip)")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("model", nargs="?", default="cnn")
    p.add_argument("data", nargs="?", default="mnist")
    p.add_argument("--dist-option", default="plain",
                   choices=["plain", "fp16", "partialUpdate", "sparseTopK",
                            "sparseThreshold"])
    p.add_argument("--spars", type=float, default=0.05)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.005)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--n-train", type=int, default=512)
    p.add_argument("--num-devices", type=int, default=None)
    p.add_argument("--force-cpu-devices", type=int, default=0,
                   help="simulate an N-device mesh on CPU (no TPU pod here)")
    # multi-host control plane (jax.distributed; untestable single-host)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    args = p.parse_args()
    run(args)
