"""U-Net segmentation example (beyond reference parity — the reference
zoo has no segmentation family; SURVEY.md §2.4).

Synthetic task, offline-friendly like every example here: segment
bright axis-aligned rectangles out of noisy backgrounds.  The model
must localize (per-pixel labels), so the transposed-conv decoder and
skip connections do real work — predicting "all background" fails the
reported foreground IoU.

    python examples/segmentation/train.py --epochs 10
"""

import argparse
import time

import numpy as np


def make_data(n, hw, rng):
    xs = rng.randn(n, 1, hw, hw).astype(np.float32) * 0.3
    ys = np.zeros((n, hw, hw), np.int32)
    for i in range(n):
        h0, w0 = rng.randint(2, hw // 2, 2)
        hh, ww = rng.randint(8, hw // 2, 2)
        xs[i, 0, h0:h0 + hh, w0:w0 + ww] += 1.5
        ys[i, h0:h0 + hh, w0:w0 + ww] = 1
    return xs, ys


def run(args):
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.unet import unet

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(args.seed)
    rng = np.random.RandomState(args.seed)
    xs, ys = make_data(args.n_train, args.hw, rng)
    xe, ye = make_data(args.n_eval, args.hw, rng)

    m = unet(num_classes=2, base_channels=args.base_channels,
             depth=args.depth)
    m.set_optimizer(opt.Adam(lr=args.lr))
    x0 = tensor.from_numpy(xs[:args.batch], dev)
    m.compile([x0], is_train=True, use_graph=args.use_graph)

    steps = args.n_train // args.batch
    for epoch in range(args.epochs):
        t0 = time.time()
        perm = rng.permutation(args.n_train)
        tot = 0.0
        for s in range(steps):
            idx = perm[s * args.batch:(s + 1) * args.batch]
            _, loss = m(tensor.from_numpy(xs[idx], dev),
                        tensor.from_numpy(ys[idx], dev))
            tot += float(tensor.to_numpy(loss))
        print(f"epoch {epoch}: loss={tot / steps:.4f} "
              f"time={time.time() - t0:.3f}s")

    m.eval()
    pred = np.argmax(
        tensor.to_numpy(m.forward(tensor.from_numpy(xe, dev))), axis=1)
    pix = float(np.mean(pred == ye))
    inter = np.logical_and(pred == 1, ye == 1).sum()
    union = np.logical_or(pred == 1, ye == 1).sum()
    print(f"eval pixel accuracy: {pix:.4f}  foreground IoU: "
          f"{inter / max(union, 1):.4f}")
    assert pix > 0.85, pix


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--n-train", type=int, default=128)
    p.add_argument("--n-eval", type=int, default=32)
    p.add_argument("--hw", type=int, default=32)
    p.add_argument("--base-channels", type=int, default=8)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--use-graph", action="store_true", default=True)
    p.add_argument("--eager", dest="use_graph", action="store_false")
    run(p.parse_args())
