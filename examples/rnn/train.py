"""char-RNN training (reference: examples/rnn char-rnn LSTM over a text
corpus, unverified — config #3).  No network here, so the default corpus
is this repository's own documentation.

    python examples/rnn/train.py [--use-graph] [--corpus FILE]
"""

import argparse
import time

import numpy as np


from singa_tpu import device, opt, tensor  # noqa: E402
from singa_tpu.models.char_rnn import CharRNN, one_hot  # noqa: E402


class Corpus:
    def __init__(self, path, seq_length):
        with open(path, "r", encoding="utf-8", errors="ignore") as f:
            self.raw = f.read()
        chars = sorted(set(self.raw))
        self.char2idx = {c: i for i, c in enumerate(chars)}
        self.idx2char = chars
        self.vocab_size = len(chars)
        self.data = np.array([self.char2idx[c] for c in self.raw], np.int32)
        self.seq_length = seq_length

    def batches(self, batch_size, rng):
        n = len(self.data) - self.seq_length - 1
        starts = rng.randint(0, n, (batch_size,))
        x = np.stack([self.data[s:s + self.seq_length] for s in starts])
        y = np.stack([self.data[s + 1:s + self.seq_length + 1] for s in starts])
        return x, y


def sample(m, corpus, dev, length=120, seed_text="the "):
    """Greedy sampling.  Context is padded to a fixed seq_length so every
    eval forward reuses one compiled shape."""
    m.eval()
    T = corpus.seq_length
    idx = [corpus.char2idx.get(c, 0) for c in seed_text]
    for _ in range(length):
        ctx = idx[-T:]
        n = len(ctx)
        padded = np.zeros((1, T), np.int64)
        padded[0, :n] = ctx
        x = tensor.from_numpy(one_hot(padded, corpus.vocab_size), dev)
        logits = tensor.to_numpy(m(x))  # (T, vocab)
        nxt = int(logits[n - 1].argmax())
        idx.append(nxt)
    m.train()
    return "".join(corpus.idx2char[i] for i in idx)


def run(args):
    dev = device.create_tpu_device(0) if args.device == "tpu" else \
        device.get_default_device()
    dev.SetRandSeed(args.seed)
    rng = np.random.RandomState(args.seed)

    corpus = Corpus(args.corpus, args.seq_length)
    print(f"corpus: {len(corpus.raw)} chars, vocab {corpus.vocab_size}")

    m = CharRNN(corpus.vocab_size, hidden_size=args.hidden_size,
                num_layers=args.num_layers, seq_length=args.seq_length,
                cell=args.cell)
    m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    x0 = tensor.Tensor((args.batch_size, args.seq_length, corpus.vocab_size),
                       dev)
    m.compile([x0], is_train=True, use_graph=args.use_graph)

    for epoch in range(args.epochs):
        t0 = time.time()
        tot = 0.0
        for _ in range(args.iters):
            xb, yb = corpus.batches(args.batch_size, rng)
            x = tensor.from_numpy(one_hot(xb, corpus.vocab_size), dev)
            y = tensor.from_numpy(yb, dev)
            _, loss = m(x, y)
            tot += float(loss.data)
        dt = time.time() - t0
        cps = args.iters * args.batch_size * args.seq_length / dt
        print(f"epoch {epoch}: loss={tot / args.iters:.4f} "
              f"time={dt:.2f}s ({cps:.0f} chars/s)")
    print("sample:", repr(sample(m, corpus, dev)[:100]))


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    default_corpus = __file__.rsplit("/examples", 1)[0] + "/SURVEY.md"
    p.add_argument("--corpus", default=default_corpus)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-length", type=int, default=64)
    p.add_argument("--hidden-size", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--cell", default="lstm",
                   choices=["lstm", "gru", "vanilla_tanh", "vanilla_relu"])
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--use-graph", action="store_true", default=False)
    p.add_argument("--device", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    run(args)
